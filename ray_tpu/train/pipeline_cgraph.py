"""MPMD pipeline-parallel training engine on compiled graphs.

The successor to the dynamic actor engine in pipeline_engine.py: same
1F1B semantics, but the steady-state microbatch loop runs over
PRE-ALLOCATED cgraph channels instead of per-call ``.remote()`` task
specs — the exact hot path PR 4's compiled graphs made ~10x faster.

Shape ("Scaling Deep Learning Training with MPMD Pipeline Parallelism",
PAPERS.md): each stage actor holds resident JITTED fwd/bwd/update
programs for its (possibly several, interleaved) model chunks, plus a
compiled per-STEP op schedule loaded into the cgraph executor's
iterative mode (cgraph/executor.py). One ``engine.step(batch)`` then
drives a full interleaved 1F1B round with zero per-microbatch
scheduling, leasing, or GCS traffic:

    driver ──act──▶ [stage 0] ──act──▶ [stage 1] ─ ... ─▶ [stage P-1]
           ──tgt──────────────────────────────────────────▶   │
           ◀──loss─────────────────────────────────────────────┘
           ◀─... grads flow backward over their own channels ...─

Channels are multi-slot rings (``slots=num_microbatches``), so a whole
round's activations stream through one edge without the driver in the
loop; with ``virtual_stages > 1`` actor i hosts global chunks
``i, i+P, ...`` and runs the interleaved schedule
(parallel/pipeline.schedule_interleaved_1f1b).

Weight update ("Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training", PAPERS.md): with ``dp > 1`` replicas of the
pipeline, each stage's dp group applies a ZeRO-sharded update — grads
reduce-scatter over the host collective, each replica updates its 1/dp
parameter shard with 1/dp of the optimizer state, and all-gathers fresh
params (parallel/zero.ZeroUpdater; ``zero_update=False`` falls back to
the replicated allreduce update for A/B).

Fault contract matches compiled graphs: a stage-actor death aborts the
engine — ``step()`` raises ``CompiledGraphClosedError`` — and
``shutdown()`` releases every pre-allocated channel segment.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.core.placement_group import placement_group, remove_placement_group

from ..exceptions import (CompiledGraphClosedError, CompiledGraphError,
                          DataFeedError, GetTimeoutError)
from ..parallel.pipeline import schedule_interleaved_1f1b
from ..perf.recorder import get_recorder as _get_recorder
from ..util import metrics as _metrics
from ..util import tracing

_FLREC = _get_recorder()

_H_STEP = _metrics.Histogram(
    "ray_tpu_pipeline_step_seconds",
    "pipeline-engine full step() latency as observed by the driver",
    boundaries=_metrics.DEFAULT_BOUNDARIES, tag_keys=("engine",))

# elastic capacity (docs/FAULT_TOLERANCE.md "Elasticity"): wall-clock of
# one resize(dp±k) — drain, opt-state reshard, respawn, recompile, resume
_H_RESIZE = _metrics.Histogram(
    "ray_tpu_resize_seconds",
    "pipeline-engine resize(dp±k) end-to-end latency",
    boundaries=_metrics.DEFAULT_BOUNDARIES, tag_keys=("direction",))

DEFAULT_CHANNEL_BYTES = 1 << 20


# ---------------------------------------------------------------------------
# resident jitted programs — shared by the stage actor AND the
# single-process reference (run_reference_1f1b), so the engine's loss
# trajectory can be compared bit-for-bit against the reference
# ---------------------------------------------------------------------------


def _make_programs(fn: Callable, has_targets: bool, remat: bool):
    """(fwd, bwd) jitted programs for one model chunk.

    remat=False: fwd returns ``(out, pullback)`` — the vjp closure is a
    pytree of residuals that crosses the jit boundary and lives on the
    actor between fwd and bwd (the 1F1B in-flight activation memory);
    bwd replays it. remat=True: fwd stores only its primal inputs and
    bwd re-runs the forward inside the backward program (activation
    rematerialization — ~1/3 more FLOPs, O(inputs) residual memory).
    """
    import jax

    if not remat:
        if has_targets:
            def fwd_core(p, x, tgt):
                return jax.vjp(lambda pp, xx: fn(pp, xx, tgt), p, x)
        else:
            def fwd_core(p, x):
                return jax.vjp(fn, p, x)
        fwd = jax.jit(fwd_core)
        bwd = jax.jit(lambda pull, g: pull(g))
        return fwd, bwd

    if has_targets:
        fwd = jax.jit(lambda p, x, tgt: fn(p, x, tgt))

        def bwd_core(p, x, tgt, g):
            _, pull = jax.vjp(lambda pp, xx: fn(pp, xx, tgt), p, x)
            return pull(g)
    else:
        fwd = jax.jit(fn)

        def bwd_core(p, x, g):
            _, pull = jax.vjp(fn, p, x)
            return pull(g)
    return fwd, jax.jit(bwd_core)


def _make_update(tx):
    """Jitted replicated optimizer core: (grads, opt_state, params) ->
    (new_params, new_opt_state)."""
    import jax

    @jax.jit
    def _upd(grads, opt_state, params):
        import optax

        updates, new_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    return _upd


def run_reference_1f1b(stage_fns: Sequence[Callable],
                       stage_params: Sequence[Any],
                       tx,
                       steps: Sequence[Tuple[Sequence[Any], Sequence[Any]]],
                       remat: bool = False,
                       tied: Sequence[tuple] = ()):
    """Single-process reference executing the SAME jitted chunk programs
    in the same order/arithmetic as the compiled engine (dp=1): fwd per
    microbatch ascending, bwd per microbatch ascending, grads
    accumulated in arrival order, tied grads exchanged once, update
    scaled by 1/M. Returns ``(losses_per_step, final_stage_params)`` —
    the engine's trajectory must match this bit-for-bit at a fixed seed.
    """
    import jax

    G = len(stage_fns)
    progs = [_make_programs(fn, g == G - 1, remat)
             for g, fn in enumerate(stage_fns)]
    params = list(stage_params)
    opt_states = [jax.jit(tx.init)(p) for p in params]
    upd = _make_update(tx)
    losses_out: List[float] = []
    for mbs, tgts in steps:
        M = len(mbs)
        acc: List[Any] = [None] * G
        residuals: Dict[Tuple[int, int], Any] = {}
        step_losses = []
        for m in range(M):
            x = mbs[m]
            for g in range(G):
                fwd, _ = progs[g]
                if g == G - 1:
                    if remat:
                        out = fwd(params[g], x, tgts[m])
                        residuals[(g, m)] = (x, tgts[m])
                    else:
                        out, pull = fwd(params[g], x, tgts[m])
                        residuals[(g, m)] = pull
                else:
                    if remat:
                        out = fwd(params[g], x)
                        residuals[(g, m)] = (x,)
                    else:
                        out, pull = fwd(params[g], x)
                        residuals[(g, m)] = pull
                x = out
            step_losses.append(out)
        for m in range(M):
            import jax.numpy as jnp

            cot = jnp.float32(1.0)
            for g in reversed(range(G)):
                _, bwd = progs[g]
                res = residuals.pop((g, m))
                if remat:
                    gp, gx = bwd(params[g], *res, cot)
                else:
                    gp, gx = bwd(res, cot)
                acc[g] = gp if acc[g] is None else jax.tree.map(
                    lambda a, b: a + b, acc[g], gp)
                cot = gx
        for (gi, ki, gj, kj) in tied:
            a, b = acc[gi][ki], acc[gj][kj]
            acc[gi][ki] = a + b
            acc[gj][kj] = b + a
        scale = 1.0 / M
        for g in range(G):
            grads = jax.tree.map(lambda t: t * scale, acc[g])
            params[g], opt_states[g] = upd(grads, opt_states[g],
                                           params[g])
        losses_out.append(
            float(sum(float(l) for l in step_losses) / M))
    return losses_out, params


# ---------------------------------------------------------------------------
# elastic resharding — checkpoints move across dp widths bit-exactly
# ---------------------------------------------------------------------------


def reshard_checkpoint(ckpt: dict, dp: int) -> dict:
    """Re-shard a checkpoint payload (``save_checkpoint`` /
    ``_pull_state_grid`` shape) to a new dp width — the data plane of
    ``CompiledPipelineEngine.resize``.

    Parameters are identical across dp rows by construction (the update
    all-gathers/replicates them), so row 0's copy seeds every new row.
    Optimizer state moves by kind:

    - ``full`` (replicated tree) / ``fsdp`` (dp-replicated host arrays)
      / ``none``: row 0 replicates to every new row; growing a
      ``full``-kind state under a ``zero_update`` engine converts it to
      flat ZeRO shards (:func:`parallel.zero.flatten_opt_state`).
    - ``zero``: per-rank flat shards merge in rank order and re-split
      across the new width (pure byte movement — bit-exact); shrinking
      to dp=1 converts back to the replicated tree plane.

    ``num_microbatches`` rescales so the GLOBAL batch (dp * M
    microbatches per step) is invariant: the resized trajectory is the
    same arithmetic a fixed-size run at the new width would execute.
    """
    from ..parallel.zero import (flatten_opt_state, flatten_tree,
                                 merge_opt_shards, split_opt_state,
                                 unflatten_opt_state)

    meta = dict(ckpt["engine"])
    old_dp = int(meta["dp"])
    new_dp = int(dp)
    if new_dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    total_mb = int(meta["num_microbatches"]) * old_dp
    if total_mb % new_dp:
        raise ValueError(
            f"global batch of {total_mb} microbatches does not divide "
            f"across dp={new_dp}; valid widths divide {total_mb}")
    states = ckpt["states"]
    P = len(states[0])
    zero_update = bool(meta.get("zero_update", True))
    new_rows: List[List[dict]] = [[None] * P for _ in range(new_dp)]
    for i in range(P):
        row0 = states[0][i]
        kind = row0.get("kind", "none")
        params = row0["params"]
        params_dict = {str(v): params[v] for v in range(len(params))}
        if kind == "zero":
            shards = [states[r][i]["opt"] for r in range(old_dp)]
            flat, spec = flatten_tree(params_dict)
            merged = merge_opt_shards(shards)
            if new_dp == 1:
                # grad_codec updaters wrap their state as {"tx",
                # "master"}; dp=1 has no dp wire, so the master copy is
                # dropped and the bare optimizer state unflattens
                if isinstance(merged, dict) \
                        and set(merged) == {"tx", "master"}:
                    merged = merged["tx"]
                opts = [unflatten_opt_state(merged, spec)]
                new_kind = "full"
            else:
                opts = split_opt_state(merged, new_dp, spec.size)
                new_kind = "zero"
        elif kind == "full" and new_dp > 1 and zero_update:
            flat, spec = flatten_tree(params_dict)
            opts = split_opt_state(
                flatten_opt_state(row0["opt"], params_dict),
                new_dp, spec.size)
            new_kind = "zero"
        else:
            # none / fsdp / replicated-full: dp rows are identical copies
            opts = [row0["opt"]] * new_dp
            new_kind = kind
        for r in range(new_dp):
            new_rows[r][i] = {"params": params, "opt": opts[r],
                              "kind": new_kind}
    meta["dp"] = new_dp
    meta["num_microbatches"] = total_mb // new_dp
    return {"step": int(ckpt.get("step", 0)), "engine": meta,
            "states": new_rows}


# ---------------------------------------------------------------------------
# the stage actor
# ---------------------------------------------------------------------------


class _CGStage:
    """One pipeline stage actor: hosts ``virtual`` model chunks with
    resident jitted fwd/bwd programs, accumulates grads per chunk, and
    applies the (optionally ZeRO-sharded) optimizer update. Its methods
    are never called per-microbatch over the task plane — the cgraph
    executor's iterative loop drives them from the compiled schedule."""

    def setup(self, actor_idx: int, num_actors: int, virtual: int,
              fn_blobs: List[bytes], chunk_params: List[Any],
              chunk_meta: List[dict], tx_blob: Optional[bytes],
              remat: bool, dp: int, dp_rank: int,
              group_name: str, zero_update: bool, fsdp: int = 1,
              grad_codec: Optional[str] = None) -> bool:
        import jax

        self.idx = actor_idx
        self.num_actors = num_actors
        self.virtual = virtual
        self.meta = chunk_meta
        self.dp = dp
        self.dp_rank = dp_rank
        self.fsdp = int(fsdp)
        self.zero_update = zero_update
        self.group_name = group_name
        # dp-sync wire codec (docs/COLLECTIVES.md): block-scaled
        # quantized collectives on every grad-sync leg; None = fp32
        self.grad_codec = grad_codec
        self._jax = jax
        fns = [cloudpickle.loads(b) for b in fn_blobs]
        self._progs = [
            _make_programs(fns[v], chunk_meta[v]["last"], remat)
            for v in range(virtual)]
        self._remat = remat
        self._residuals: Dict[Tuple[int, int], Any] = {}
        self._grad_acc: Dict[str, Any] = {}
        self.tx = cloudpickle.loads(tx_blob) if tx_blob else None
        self._zero = None
        self._opt_state = None
        self._upd = None
        self._plane = None
        self._fsdp_state: Dict[str, Any] = {}
        self._fsdp_opt: Dict[str, Any] = {}
        self._param_cache: Dict[str, Any] = {}
        if self.fsdp > 1:
            # sharded execution layer (docs/SHARDING.md): this stage's
            # chunk params + optimizer moments live 1/fsdp per chip on
            # an in-actor mesh; forwards gather exactly, the update is
            # shard-local — loss trajectory bit-identical to replicated
            from ..parallel.sharding import FsdpPlane, MeshOwner

            owner = MeshOwner.fsdp_mesh(
                self.fsdp, name=f"stage{actor_idx}-r{dp_rank}")
            self._plane = FsdpPlane(owner, self.tx)
            for v in range(virtual):
                self._fsdp_state[str(v)] = self._plane.shard(
                    chunk_params[v])
            self.params = {}
        else:
            self.params = {
                str(v): chunk_params[v] for v in range(virtual)}
        if self.tx is not None:
            if dp > 1:
                from ..parallel import collective

                # the group lives for the engine run; released by
                # _destroy_collective_groups at shutdown/recover/resize
                collective.create_collective_group(  # graftcheck: disable=GC030
                    dp, dp_rank, group_name=group_name)
            if self._plane is not None:
                # fsdp composes with dp through a host-collective grad
                # sync (update() allreduces the mean before the sharded
                # step); the dp-plane ZeRO updater stays the fsdp=1 path
                for v in range(virtual):
                    self._fsdp_opt[str(v)] = self._plane.init_opt(
                        self._fsdp_state[str(v)])
            elif dp > 1 and zero_update:
                from ..parallel.zero import ZeroUpdater

                self._zero = ZeroUpdater(
                    self.tx, dp, dp_rank, group_name=group_name,
                    grad_codec=grad_codec).init(self.params)
            else:
                self._opt_state = jax.jit(self.tx.init)(self.params)
                self._upd = _make_update(self.tx)
        return True

    def _params_of(self, v: int):
        """Chunk ``v``'s full parameter tree. fsdp: gathered on demand
        from the sharded residence, cached for the step (update()
        drops the cache so only shards persist between steps)."""
        if self._plane is None:
            return self.params[str(v)]
        key = str(v)
        cached = self._param_cache.get(key)
        if cached is None:
            t0 = time.perf_counter()
            cached = self._param_cache[key] = self._plane.gather(
                self._fsdp_state[key])
            # sync-exposed fsdp gather time, drained into the step
            # report by update() (step profiler, ISSUE 17)
            self._gather_s = getattr(self, "_gather_s", 0.0) \
                + (time.perf_counter() - t0)
        return cached

    # -- schedule ops (driven by the cgraph iterative loop) ---------------

    def forward(self, v: int, mb: int, x, targets=None):
        """Chunk ``v``'s microbatch forward. Returns the activation for
        the next chunk — or, on the LAST global chunk, the scalar loss
        (which the schedule routes to the driver's loss channel)."""
        fwd, _ = self._progs[v]
        p = self._params_of(v)
        if self.meta[v]["last"]:
            if self._remat:
                out = fwd(p, x, targets)
                self._residuals[(v, mb)] = (x, targets)
            else:
                out, pull = fwd(p, x, targets)
                self._residuals[(v, mb)] = pull
        else:
            if self._remat:
                out = fwd(p, x)
                self._residuals[(v, mb)] = (x,)
            else:
                out, pull = fwd(p, x)
                self._residuals[(v, mb)] = pull
        return out

    def backward(self, v: int, mb: int, g=None):
        """Chunk ``v``'s microbatch backward: consumes the saved
        residual, accumulates this chunk's param grads, and returns the
        cotangent for the upstream chunk (None seed on the last global
        chunk — the loss pulls back from 1.0)."""
        import jax.numpy as jnp

        _, bwd = self._progs[v]
        res = self._residuals.pop((v, mb))
        if g is None:
            g = jnp.float32(1.0)
        if self._remat:
            gp, gx = bwd(self._params_of(v), *res, g)
        else:
            gp, gx = bwd(res, g)
        key = str(v)
        if key not in self._grad_acc or self._grad_acc[key] is None:
            self._grad_acc[key] = gp
        else:
            self._grad_acc[key] = self._jax.tree.map(
                lambda a, b: a + b, self._grad_acc[key], gp)
        return gx

    def tied_grad(self, v: int, key: str):
        """Ship this chunk's accumulated grad for a tied weight to the
        partner chunk (Megatron-style tied-embedding exchange)."""
        return self._grad_acc[str(v)][key]

    def tied_add(self, v: int, key: str, g) -> bool:
        self._grad_acc[str(v)][key] = self._grad_acc[str(v)][key] + g
        return True

    def update(self, scale: float) -> dict:
        """End-of-step optimizer update over every hosted chunk. With a
        dp group: ZeRO reduce-scatter/shard-update/all-gather (or the
        replicated allreduce update when zero_update=False). Returns the
        stage report shipped to the driver."""
        t0 = time.perf_counter()
        grads = {k: self._jax.tree.map(lambda t: t * scale, v)
                 for k, v in self._grad_acc.items()}
        from ..parallel.zero import tree_bytes

        sync = {"rs_ms": 0.0, "ag_ms": 0.0, "allreduce_ms": 0.0,
                "gather_ms": round(
                    getattr(self, "_gather_s", 0.0) * 1e3, 3)}
        self._gather_s = 0.0
        if self.tx is None:
            self._param_cache = {}  # evaluation engine: grads dropped
        elif self._plane is not None:
            # fsdp plane: dp-sync the full grads first (host allreduce
            # mean — same arithmetic as the replicated path), then the
            # shard-local sharded update; the per-step gather cache
            # drops so only 1/fsdp params+moments persist
            if self.dp > 1:
                import jax.numpy as jnp
                import numpy as np

                from ..parallel import collective
                from ..parallel.zero import flatten_tree, unflatten_tree

                flat_g, spec = flatten_tree(grads)
                t_ar = time.perf_counter()
                mean = collective.allreduce(
                    np.asarray(flat_g), self.group_name,
                    codec=self.grad_codec) / self.dp
                sync["allreduce_ms"] = round(
                    (time.perf_counter() - t_ar) * 1e3, 3)
                grads = unflatten_tree(
                    jnp.asarray(mean, dtype=spec.dtype), spec)
            for v in range(self.virtual):
                key = str(v)
                self._fsdp_state[key], self._fsdp_opt[key] = \
                    self._plane.update(self._fsdp_state[key],
                                       grads[key],
                                       self._fsdp_opt[key])
            self._param_cache = {}
        elif self._zero is not None:
            self.params = self._zero.update(self.params, grads)
            sync["rs_ms"] = round(self._zero.last_rs_s * 1e3, 3)
            sync["ag_ms"] = round(self._zero.last_ag_s * 1e3, 3)
        elif self.dp > 1:
            # replicated A/B path: allreduce-mean over the flat vector,
            # full-tree update on every replica (full opt state each)
            import jax.numpy as jnp

            from ..parallel import collective
            from ..parallel.zero import flatten_tree, unflatten_tree

            flat_g, spec = flatten_tree(grads)
            import numpy as np

            t_ar = time.perf_counter()
            mean = collective.allreduce(
                np.asarray(flat_g), self.group_name,
                codec=self.grad_codec) / self.dp
            sync["allreduce_ms"] = round(
                (time.perf_counter() - t_ar) * 1e3, 3)
            grads = unflatten_tree(
                jnp.asarray(mean, dtype=spec.dtype), spec)
            self.params, self._opt_state = self._upd(
                grads, self._opt_state, self.params)
        else:
            self.params, self._opt_state = self._upd(
                grads, self._opt_state, self.params)
        self._grad_acc = {}
        report = {
            "stage": self.idx, "dp_rank": self.dp_rank,
            "update_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "opt_state_bytes": self.opt_state_bytes(),
            "in_flight_residuals": len(self._residuals),
            # collective sync-exposed ms: ZeRO reduce-scatter/all-gather
            # legs, dp allreduce, fsdp gather — the ROADMAP overlap-
            # scheduling arc's target series (step profiler, ISSUE 17)
            "sync_ms": round(sum(sync.values()), 3),
            "sync_breakdown": sync,
        }
        # per-op wall spans + cumulative exec/bubble recorded by the
        # cgraph executor in THIS process (perf/oplog.py); update() is
        # the last op of the step schedule on the same thread, so the
        # drain rides the existing report channel to the driver
        from ..perf import oplog as _oplog

        report["perf"] = _oplog.stage_perf(f"{self.dp_rank}.{self.idx}")
        if self._plane is not None:
            per_chip: Dict[int, int] = {}
            for v in range(self.virtual):
                key = str(v)
                for dev, b in self._plane.per_device_bytes(
                        self._fsdp_state[key],
                        self._fsdp_opt.get(key)).items():
                    per_chip[dev] = per_chip.get(dev, 0) + b
            report["fsdp"] = self.fsdp
            report["fsdp_bytes_per_chip"] = {
                str(d): b for d, b in sorted(per_chip.items())}
        return report

    # -- dynamic-path surface (driver calls between steps) ----------------

    def get_params(self) -> List[Any]:
        if self._plane is not None:
            # transient gather, NOT through the step cache: a between-
            # steps inspection must not leave full params resident
            return [self._plane.gather(self._fsdp_state[str(v)])
                    for v in range(self.virtual)]
        return [self.params[str(v)] for v in range(self.virtual)]

    def get_state(self) -> dict:
        """Checkpoint payload for this actor: hosted chunk params plus
        the optimizer state it owns — the full tree when replicated, the
        1/dp SHARD when ZeRO-sharded (each dp rank persists its own
        shard; restore hands each rank its shard back). Pulled by the
        driver BETWEEN steps, when no residuals are in flight."""
        import numpy as np_mod

        import jax

        def host(t):
            # device -> host copies: the checkpoint must not pin device
            # buffers, and numpy pickles leaner than jax.Array
            return jax.tree.map(np_mod.asarray, t)

        if self._plane is not None:
            # plane.to_host: full (gathered) params; opt moments as
            # globally-shaped flat arrays — restore re-shards both
            # (same fsdp width, enforced by the engine geometry check)
            params, opt = [], {}
            for v in range(self.virtual):
                p, o = self._plane.to_host(
                    self._fsdp_state[str(v)], self._fsdp_opt.get(str(v)))
                params.append(p)
                if o is not None:
                    opt[str(v)] = o
            return {"params": params, "opt": opt or None, "kind": "fsdp"}
        if self._zero is not None:
            opt, kind = host(self._zero.opt_state()), "zero"
        elif self._opt_state is not None:
            opt, kind = host(self._opt_state), "full"
        else:
            opt, kind = None, "none"
        return {"params": [host(self.params[str(v)])
                           for v in range(self.virtual)],
                "opt": opt, "kind": kind}

    def load_state(self, chunk_params: Optional[List[Any]], opt_state,
                   kind: str) -> bool:
        """Restore a get_state() payload: params replace the hosted
        chunks (None = keep what setup() installed — the recover path
        ships checkpoint params through setup already and must not pay
        the serialization twice), optimizer state replaces what setup()
        initialized, and any in-flight residual/grad accumulation is
        discarded (restore happens at a step boundary by construction)."""
        if chunk_params is not None:
            if self._plane is not None:
                for v in range(self.virtual):
                    self._fsdp_state[str(v)] = self._plane.shard(
                        chunk_params[v])
            else:
                self.params = {str(v): chunk_params[v]
                               for v in range(self.virtual)}
        self._residuals = {}
        self._grad_acc = {}
        self._param_cache = {}
        if kind == "fsdp":
            if self._plane is None:
                raise ValueError(
                    "checkpoint holds fsdp-sharded state but this stage "
                    "runs unsharded (fsdp flag changed between save and "
                    "restore)")
            if opt_state is not None:
                for v in range(self.virtual):
                    self._fsdp_opt[str(v)] = self._plane.place_opt(
                        self._fsdp_state[str(v)], opt_state[str(v)])
        elif kind == "zero":
            if self._zero is None:
                raise ValueError(
                    "checkpoint holds a ZeRO opt-state shard but this "
                    "stage runs a replicated update (zero_update flag "
                    "changed between save and restore)")
            self._zero.set_opt_state(opt_state)
        elif kind == "full":
            if self._opt_state is None:
                raise ValueError(
                    "checkpoint holds a replicated opt state but this "
                    "stage is ZeRO-sharded or has no optimizer")
            self._opt_state = opt_state
        return True

    def opt_state_bytes(self) -> int:
        from ..parallel.zero import tree_bytes

        if self._plane is not None:
            return sum(tree_bytes(o) for o in self._fsdp_opt.values())
        if self._zero is not None:
            return self._zero.opt_state_bytes()
        return tree_bytes(self._opt_state) \
            if self._opt_state is not None else 0

    def cleanup(self) -> bool:
        """Tear down this stage's dp collective group (rank 0 kills the
        rendezvous store so nothing detached outlives the engine)."""
        if self.dp > 1 and self.dp_rank == 0:
            from ..parallel import collective

            collective.destroy_collective_group(self.group_name)
        return True


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class _StagePlan:
    __slots__ = ("actor_id", "node", "worker", "handle", "in_specs",
                 "nodes", "stage", "replica", "_report_w")

    def __init__(self, actor_id, node, worker, handle, stage, replica):
        self.actor_id = actor_id
        self.node = node
        self.worker = worker
        self.handle = handle
        self.stage = stage
        self.replica = replica
        self.in_specs: List[dict] = []
        self.nodes: List[dict] = []
        self._report_w = None


class CompiledPipelineEngine:
    """Drives ``dp`` replicas x ``P`` stage actors through interleaved
    1F1B over pre-allocated cgraph channels.

    stage_fns: G = P * virtual_stages chunk callables in global order.
        Chunks 0..G-2: ``fn(params, x) -> activation``; the last chunk:
        ``fn(params, x, targets) -> scalar loss`` (G == 1 collapses both
        into the last-chunk signature — a pure-dp engine).
    stage_params: G parameter pytrees (one per chunk).
    tx: optax optimizer (None = forward/backward only, no update).
    num_microbatches: 1F1B round size M; ``step()`` takes dp*M
        microbatches (contiguous M-slices per dp replica).
    virtual_stages: model chunks per actor (interleaved 1F1B when > 1).
    dp: data-parallel pipeline replicas; each stage's dp group syncs
        grads at update time.
    fsdp: in-jit sharded param/opt-state axis INSIDE each stage actor
        (parallel.sharding.FsdpPlane over the host's chips): chunk
        params and optimizer moments live 1/fsdp per chip, forwards
        gather exactly, the update is shard-local — loss trajectory
        bit-identical to fsdp=1. Composes with dp (host grad sync) and
        the pipeline stages into pp x dp x fsdp (docs/SHARDING.md).
    zero_update: ZeRO-shard the dp update (1/dp optimizer state per
        replica) vs the replicated allreduce update (fsdp=1 path; with
        fsdp > 1 the sharded update runs on the fsdp plane instead).
    grad_codec: block-scaled wire codec ("int8"/"e4m3",
        docs/COLLECTIVES.md) for the dp gradient sync — the ZeRO
        reduce-scatter/all-gather (fp32 master shards) or the
        replicated/fsdp allreduce ship quantized payloads, ~1/4 the
        bytes over the dp wire; None (default) = full precision,
        bit-identical to the pre-codec engine.
    wire_codec: same codec vocabulary applied to the cgraph CHANNEL
        payloads — pipeline activations and cotangents cross their
        hops block-quantized (large float arrays only; small/non-float
        payloads like losses and reports pass through raw). Lossy by
        construction; seq/error-envelope semantics are unchanged.
    remat: recompute chunk forwards in the backward instead of holding
        vjp residuals (activation rematerialization knob).
    tied: [(chunk_i, key_i, chunk_j, key_j), ...] tied-weight pairs
        whose grads are exchanged and summed before each update.
    checkpoint_dir: non-empty => the engine can persist per-stage params
        + optimizer state (ZeRO shards stay sharded) to this directory
        with atomic rename-commit; with checkpoint_every > 0 a snapshot
        is pulled off the actors after every Nth step and written on a
        background thread (the pull is synchronous — between steps — so
        the snapshot is a consistent step boundary; only the disk IO is
        async). ``recover()`` restores from the newest commit, and the
        restored trajectory is bit-identical to a clean restart from the
        same checkpoint (docs/FAULT_TOLERANCE.md).
    """

    def __init__(self, stage_fns: Sequence[Callable],
                 stage_params: Sequence[Any],
                 tx=None, *,
                 num_microbatches: int,
                 virtual_stages: int = 1,
                 dp: int = 1,
                 fsdp: int = 1,
                 zero_update: bool = True,
                 grad_codec: Optional[str] = None,
                 wire_codec: Optional[str] = None,
                 remat: bool = False,
                 tied: Sequence[tuple] = (),
                 channel_bytes: int = DEFAULT_CHANNEL_BYTES,
                 resources_per_stage: Optional[dict] = None,
                 scheduling_strategies: Optional[Sequence] = None,
                 setup_timeout: float = 120.0,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0):
        G = len(stage_fns)
        V = int(virtual_stages)
        if G < 1 or len(stage_params) != G:
            raise ValueError("need one param tree per stage fn")
        if V < 1 or G % V:
            raise ValueError(
                f"{G} chunks not divisible into virtual_stages={V}")
        M = int(num_microbatches)
        if M < 1:
            raise ValueError("num_microbatches must be >= 1")
        self.num_chunks = G
        self.num_stages = G // V
        self.virtual = V
        self.num_microbatches = M
        self.dp = int(dp)
        self.fsdp = int(fsdp)
        if self.fsdp < 1:
            raise ValueError(f"fsdp must be >= 1, got {fsdp}")
        self.zero_update = bool(zero_update)
        from ..parallel.quant import check_codec

        self.grad_codec = check_codec(grad_codec)
        self.wire_codec = check_codec(wire_codec)
        self.tied = list(tied)
        self.graph_id = os.urandom(16)
        self._gtag = self.graph_id.hex()[:8]
        self._channel_bytes = int(channel_bytes)
        self._lock = threading.Lock()
        # serializes the teardown BODY (not just the torn flag): an abort
        # tears down on a background thread, and a concurrent shutdown()
        # must block until the channels are actually released. REENTRANT:
        # a signal handler or close-callback re-entering teardown on the
        # thread already inside it must return (via the torn flag), not
        # self-deadlock.
        self._teardown_lock = threading.RLock()
        self._stop = threading.Event()
        # fault-recovery state: everything needed to respawn stages and
        # recompile channels after a kill (docs/FAULT_TOLERANCE.md)
        self._fn_blobs = [cloudpickle.dumps(fn) for fn in stage_fns]
        self._tx_blob = cloudpickle.dumps(tx) if tx is not None else None
        self._init_params = list(stage_params)
        self._remat = bool(remat)
        self._res = resources_per_stage
        self._strategies = scheduling_strategies
        self._setup_timeout = float(setup_timeout)
        self.checkpoint_dir = checkpoint_dir or None
        self.checkpoint_every = int(checkpoint_every)
        if self.checkpoint_dir:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
        self._step_count = 0
        self.last_checkpoint_path: Optional[str] = None
        self._latest_step = -1
        self._ckpt_lock = threading.Lock()
        self._ckpt_pending: List[threading.Thread] = []
        self._shutdown_done = False
        self._torn = False
        self._poisoned: Optional[Exception] = None
        self._closed_error: Optional[Exception] = None
        self._alloc: List[Tuple[Any, Any]] = []
        self._unsub = None
        self._actor_plans: Dict[bytes, _StagePlan] = {}
        self._in_writers: List[Any] = []      # per dp replica
        self._tgt_writers: List[Any] = []
        self._loss_readers: List[Any] = []
        self._report_readers: List[List[Any]] = []  # [r][stage]
        self._qreaders: Dict[str, Any] = {}
        # data feed (ray_tpu/data/feed.py): writer specs for the input
        # edges, retained at compile time so attach_feed can hand the
        # producer role to pump actors; the feed descriptor survives
        # recover() (which re-attaches), the pump actors do not
        self._edge_specs: Dict[str, dict] = {}
        self._feed = None
        self._feed_base_step = 0  # _step_count at attach: drain accounting
        self._feed_actors: List[Any] = []
        self._feed_actor_ids: set = set()
        self.last_reports: List[dict] = []
        self.last_step_s: float = 0.0
        self._pg = None

        from ..core import runtime as runtime_mod

        rt = runtime_mod.get_runtime()
        if not hasattr(rt, "gcs"):
            raise CompiledGraphError(
                "CompiledPipelineEngine must be built on the driver")
        self._rt = rt

        try:
            self._spawn_actors(self._init_params)
            self._compile()
        except BaseException:
            try:
                self.shutdown()
            except Exception:
                pass
            raise
        if self.checkpoint_dir and self.checkpoint_every > 0:
            # step-0 commit: recover() always has a restore point, and a
            # restart-from-scratch replays the same trajectory
            self.save_checkpoint()

    # -- construction ------------------------------------------------------

    def _spawn_actors(self, chunk_params: Sequence[Any],
                      per_actor_state: Optional[List[List[dict]]] = None
                      ) -> None:
        """Spawn dp x P stage actors and run setup. ``chunk_params`` are
        G parameter pytrees in global chunk order; ``per_actor_state``
        (recover/restore path) additionally carries each actor's
        get_state() payload — params land via setup, optimizer state via
        load_state afterwards. Reuses an existing placement group (the
        recover path respawns into the same bundles)."""
        P, V, dp = self.num_stages, self.virtual, self.dp
        res = dict(self._res or {"CPU": 1.0})
        strategies = self._strategies
        actor_cls = ray_tpu.remote(_CGStage)
        if strategies is None and self._pg is None:
            self._pg = placement_group(
                [dict(res) for _ in range(P * dp)], strategy="SPREAD")
            if not self._pg.ready(timeout=60):
                raise TimeoutError(
                    "pipeline placement group not ready")
        self.actors: List[Any] = []
        self.actor_grid: List[List[Any]] = []
        setups = []
        for r in range(dp):
            row = []
            for i in range(P):
                flat = r * P + i
                if strategies is not None:
                    a = actor_cls.options(
                        num_cpus=res.get("CPU", 1.0),
                        scheduling_strategy=strategies[flat]).remote()
                else:
                    a = actor_cls.options(
                        num_cpus=res.get("CPU", 1.0),
                        placement_group=self._pg,
                        placement_group_bundle_index=flat).remote()
                row.append(a)
                self.actors.append(a)
                chunks = [i + v * P for v in range(V)]
                meta = [{"global": g, "first": g == 0,
                         "last": g == self.num_chunks - 1}
                        for g in chunks]
                if per_actor_state is not None:
                    cp = per_actor_state[r][i]["params"]
                else:
                    cp = [chunk_params[g] for g in chunks]
                setups.append(a.setup.remote(
                    i, P, V,
                    [self._fn_blobs[g] for g in chunks],
                    cp, meta, self._tx_blob,
                    self._remat, dp, r, f"zpipe-{self._gtag}-s{i}",
                    self.zero_update, self.fsdp, self.grad_codec))
            self.actor_grid.append(row)
        ray_tpu.get(setups, timeout=self._setup_timeout)
        if per_actor_state is not None:
            loads = []
            for r in range(dp):
                for i in range(P):
                    st = per_actor_state[r][i]
                    # params already traveled through setup(); ship only
                    # the optimizer state on this second hop
                    loads.append(self.actor_grid[r][i].load_state.remote(
                        None, st["opt"], st["kind"]))
            ray_tpu.get(loads, timeout=self._setup_timeout)

    def _compile(self) -> None:
        from ..cgraph.channel import (QueueChannel, RpcSender, ShmChannel,
                                      segment_size)
        from ..core.ids import ObjectId
        from ..core.object_store import SegmentReader

        rt = self._rt
        P, V, dp, M = (self.num_stages, self.virtual, self.dp,
                       self.num_microbatches)
        G = self.num_chunks
        self._segreader = SegmentReader()

        # resolve each actor's placement once (cgraph/compiled.py rules)
        plans: List[List[_StagePlan]] = []
        for r in range(dp):
            row = []
            for i in range(P):
                h = self.actor_grid[r][i]
                if rt._cgraph_actor_in_use(h._actor_id):
                    raise CompiledGraphError(
                        f"actor {h._actor_id.hex()[:8]} already "
                        f"participates in another live compiled graph")
                rt.wait_for_actor(h._actor_id, timeout=60.0)
                rec = rt._actors.get(h._actor_id)
                if rec is None or rec.worker is None \
                        or rec.node_id is None:
                    raise CompiledGraphError(
                        f"stage actor {h._actor_id.hex()[:8]} has no "
                        f"resident worker to compile onto")
                node = rt.nodes.get(rec.node_id)
                if node is None or not node.alive:
                    raise CompiledGraphError(
                        f"stage actor {h._actor_id.hex()[:8]}'s node "
                        f"is gone")
                plan = _StagePlan(h._actor_id, node, rec.worker, h, i, r)
                self._actor_plans[h._actor_id.binary()] = plan
                row.append(plan)
            plans.append(row)
        self._plans = plans

        def alloc_on(node, slots):
            cid = ObjectId.from_random()
            size = segment_size(self._channel_bytes, slots)
            if getattr(node, "is_remote", False):
                name = node.channel.call(
                    "cgraph_alloc_channel",
                    {"cid": cid, "size": size}, timeout=30)
            else:
                name = node.store.allocate_channel(cid, size)
            self._alloc.append((node, cid))
            return cid, name, size

        def make_edge(producer, consumer, edge, slots):
            """producer/consumer: "driver" or _StagePlan. Returns
            (writer_spec_or_endpoint, reader_spec_or_endpoint) — dict
            specs for plan sides, live endpoints for driver sides."""
            pnode = None if producer == "driver" else producer.node
            cnode = None if consumer == "driver" else consumer.node
            anode = cnode if cnode is not None else pnode
            same_host = (
                (pnode is None and not getattr(cnode, "is_remote",
                                               False))
                or (cnode is None and not getattr(pnode, "is_remote",
                                                  False))
                or (pnode is not None and pnode is cnode))
            if same_host:
                cid, name, size = alloc_on(anode, slots)
                spec = {"kind": "shm", "name": name, "size": size,
                        "slots": slots, "cid": cid.hex(), "edge": edge}
                if producer == "driver":
                    # retain the writer spec: attach_feed hands the
                    # producer role to a pump actor by re-opening this
                    # segment (the seq ledger is segment-resident)
                    self._edge_specs[edge] = dict(spec)
                wr = spec if producer != "driver" else ShmChannel(
                    self._segreader, name, size, edge=edge,
                    interrupt=self._stop, slots=slots)
                rd = dict(spec) if consumer != "driver" else ShmChannel(
                    self._segreader, name, size, edge=edge,
                    interrupt=self._stop, slots=slots)
                return wr, rd
            cid = ObjectId.from_random()
            if consumer == "driver":
                q = QueueChannel(cid.hex(), edge=edge,
                                 interrupt=self._stop)
                self._qreaders[cid.hex()] = q
                rt._cgraph_routes[cid.hex()] = (
                    "driver", self, None, self.graph_id)
                return {"kind": "rpc", "cid": cid.hex(),
                        "edge": edge}, q
            rt._cgraph_routes[cid.hex()] = (
                "worker", consumer.node, consumer.worker, self.graph_id)
            rspec = {"kind": "queue", "cid": cid.hex(), "edge": edge}
            if producer == "driver":
                gid = self.graph_id
                # retain an rpc writer spec: a pump actor ships the same
                # envelopes up its control channel (cgraph_send) and the
                # head routes them here, continuing at the handed-off seq
                self._edge_specs[edge] = {"kind": "rpc",
                                          "cid": cid.hex(), "edge": edge}

                def send(chan_id, seq, data, _c=consumer):
                    _c.node.worker_notify(
                        _c.worker, "cgraph_push",
                        {"graph_id": gid, "cid": chan_id,
                         "seq": seq, "data": data})

                return RpcSender(send, cid.hex(), edge=edge), rspec
            return {"kind": "rpc", "cid": cid.hex(), "edge": edge}, rspec

        def plan_of(r, g):
            return plans[r][g % P]

        # -- wire every edge, per dp replica ------------------------------
        sched = schedule_interleaved_1f1b(P, M, V)
        for r in range(dp):
            fwd_w: Dict[int, Any] = {}   # chunk g -> writer spec at g
            fwd_r: Dict[int, Any] = {}   # chunk g -> reader spec at g
            bwd_w: Dict[int, Any] = {}
            bwd_r: Dict[int, Any] = {}
            # activations: driver -> chunk0, chunk g -> g+1, loss -> driver
            wr, rd = make_edge("driver", plan_of(r, 0),
                               f"r{r}:in->c0", M)
            self._in_writers.append(wr)
            plan_of(r, 0).in_specs.append(rd)
            fwd_r[0] = rd
            for g in range(G - 1):
                wr, rd = make_edge(plan_of(r, g), plan_of(r, g + 1),
                                   f"r{r}:c{g}->c{g + 1}", M)
                fwd_w[g] = wr
                plan_of(r, g + 1).in_specs.append(rd)
                fwd_r[g + 1] = rd
            wr, rd = make_edge(plan_of(r, G - 1), "driver",
                               f"r{r}:c{G - 1}->loss", M)
            fwd_w[G - 1] = wr
            self._loss_readers.append(rd)
            # targets: driver -> last chunk's actor
            wr, rd = make_edge("driver", plan_of(r, G - 1),
                               f"r{r}:in->targets", M)
            self._tgt_writers.append(wr)
            plan_of(r, G - 1).in_specs.append(rd)
            tgt_r = rd
            # cotangents: chunk g -> g-1
            for g in range(1, G):
                wr, rd = make_edge(plan_of(r, g), plan_of(r, g - 1),
                                   f"r{r}:c{g}->c{g - 1}:grad", M)
                bwd_w[g] = wr
                plan_of(r, g - 1).in_specs.append(rd)
                bwd_r[g - 1] = rd
            # tied-grad exchange channels (both directions per pair)
            tied_w: Dict[tuple, Any] = {}
            tied_r: Dict[tuple, Any] = {}
            n_tied: Dict[tuple, int] = {}
            for (gi, ki, gj, kj) in self.tied:
                for a, b in ((gi, gj), (gj, gi)):
                    n_tied[(a, b)] = n_tied.get((a, b), 0) + 1
            for (a, b), cnt in n_tied.items():
                wr, rd = make_edge(plan_of(r, a), plan_of(r, b),
                                   f"r{r}:tied:c{a}->c{b}", cnt)
                tied_w[(a, b)] = wr
                plan_of(r, b).in_specs.append(rd)
                tied_r[(a, b)] = rd
            # per-stage end-of-step report to the driver
            reports = []
            for i in range(P):
                wr, rd = make_edge(plans[r][i], "driver",
                                   f"r{r}:s{i}->report", 2)
                reports.append(rd)
                plans[r][i]._report_w = wr
            self._report_readers.append(reports)

            # -- per-actor op schedules into node plans -------------------
            from ..core import serialization

            def const(v):
                return ("const", serialization.dumps(v))

            for i in range(P):
                plan = plans[r][i]
                ops: List[dict] = []
                for kind, v, mb in sched[i]:
                    g = v * P + i
                    # wire_codec compresses the activation/cotangent
                    # hops — fwd/bwd outputs; the loss envelope off the
                    # last chunk is a scalar and passes through raw
                    # under the codec's size floor anyway
                    codec = self.wire_codec
                    if kind == "fwd":
                        args = [const(v), const(mb)]
                        args.append(("chan", fwd_r[g]["cid"]))
                        if g == G - 1:
                            args.append(("chan", tgt_r["cid"]))
                        outs = [fwd_w[g]] if g in fwd_w else []
                        ops.append({"key": f"f{g}.{mb}",
                                    "method": "forward",
                                    "num_returns": 1,
                                    "concurrency_group": "",
                                    "codec": codec,
                                    "args": args, "kwargs": {},
                                    "outs": outs})
                    else:
                        args = [const(v), const(mb)]
                        if g < G - 1:
                            args.append(("chan", bwd_r[g]["cid"]))
                        outs = [bwd_w[g]] if g in bwd_w else []
                        ops.append({"key": f"b{g}.{mb}",
                                    "method": "backward",
                                    "num_returns": 1,
                                    "concurrency_group": "",
                                    "codec": codec,
                                    "args": args, "kwargs": {},
                                    "outs": outs})
                # tied exchange: all sends first, then all receives —
                # single-pass, deadlock-free for any pair structure
                for (gi, ki, gj, kj) in self.tied:
                    for g_send, key, g_peer in ((gi, ki, gj),
                                                (gj, kj, gi)):
                        if g_send % P != i:
                            continue
                        ops.append({
                            "key": f"tg{g_send}.{key}",
                            "method": "tied_grad", "num_returns": 1,
                            "concurrency_group": "",
                            "args": [const(g_send // P), const(key)],
                            "kwargs": {},
                            "outs": [tied_w[(g_send, g_peer)]]})
                for (gi, ki, gj, kj) in self.tied:
                    for g_recv, key, g_peer in ((gi, ki, gj),
                                                (gj, kj, gi)):
                        if g_recv % P != i:
                            continue
                        ops.append({
                            "key": f"ta{g_recv}.{key}",
                            "method": "tied_add", "num_returns": 1,
                            "concurrency_group": "",
                            "args": [const(g_recv // P), const(key),
                                     ("chan",
                                      tied_r[(g_peer, g_recv)]["cid"])],
                            "kwargs": {}, "outs": []})
                ops.append({"key": f"u{i}", "method": "update",
                            "num_returns": 1, "concurrency_group": "",
                            "args": [const(1.0 / M)], "kwargs": {},
                            "outs": [plan._report_w]})
                plan.nodes = ops

        # -- register + load (routes must exist before loops start) -------
        rt._cgraph_register(self)
        for plan in self._actor_plans.values():
            payload = {"graph_id": self.graph_id,
                       "actor_id": plan.actor_id,
                       "iterative": True,
                       "stage": f"{plan.replica}.{plan.stage}",
                       "in_channels": plan.in_specs,
                       "nodes": plan.nodes}
            plan.node.worker_cgraph_call(plan.worker, "cgraph_load",
                                         payload, timeout=30.0)
        self._unsub = rt.gcs.pubsub.subscribe("actor",
                                              self._on_actor_event)

    # -- execution surface -------------------------------------------------

    def step(self, microbatches: Optional[Sequence[Any]] = None,
             targets: Optional[Sequence[Any]] = None,
             timeout: float = 300.0) -> float:
        """One full (interleaved) 1F1B training step. Takes dp * M
        microbatches/targets — replica r consumes the contiguous slice
        ``[r*M:(r+1)*M]``. Returns the mean loss across every
        microbatch of every replica.

        With a feed attached (:meth:`attach_feed`) call ``step()`` with
        NO batch: the pump actors already keep the input rings resident,
        so this only reads losses/reports — zero driver sends, zero
        ``.remote()`` dispatches in steady state."""
        # hands-off elasticity: a preemption notice / node join observed
        # since the last step resizes dp HERE, at the step boundary —
        # the global batch (dp * M) is invariant, so callers never
        # change what they feed
        self._apply_pending_resize()
        M, dp = self.num_microbatches, self.dp
        fed = self._feed is not None
        if fed:
            if microbatches is not None or targets is not None:
                raise ValueError(
                    "a feed is attached — step() takes no batch "
                    "(detach_feed() to hand-feed again)")
        else:
            if microbatches is None or targets is None:
                raise ValueError(
                    "step() needs microbatches and targets (or attach "
                    "a feed first)")
            if len(microbatches) != M * dp or len(targets) != M * dp:
                raise ValueError(
                    f"step() needs num_microbatches*dp = {M * dp} "
                    f"microbatches, got {len(microbatches)}")
        with self._lock:
            self._check_open()
        from ..cgraph.channel import FLAG_ERROR, pack_envelope, \
            unpack_envelope
        from ..cgraph.codec import decode_value
        from ..core import serialization

        deadline = time.monotonic() + timeout
        ctx = tracing.current_context()
        trace = f"{ctx[0]}:{ctx[1]}" if ctx else ""
        if not fed:
            self._last_step_inputs = (microbatches, targets)
        if _FLREC.enabled:
            _FLREC.record("pipeline.step.begin", self._gtag,
                          {"step": self._step_count})
        t0 = time.perf_counter()
        try:
            if not fed:
                for r in range(dp):
                    for m in range(M):
                        k = r * M + m
                        self._in_writers[r].send(
                            pack_envelope(0, trace,
                                          serialization.dumps(
                                              microbatches[k])),
                            timeout=max(0.0,
                                        deadline - time.monotonic()))
                        self._tgt_writers[r].send(
                            pack_envelope(0, trace,
                                          serialization.dumps(
                                              targets[k])),
                            timeout=max(0.0,
                                        deadline - time.monotonic()))
            losses: List[Any] = []
            first_err = None
            for r in range(dp):
                for m in range(M):
                    data = self._loss_readers[r].recv(
                        timeout=max(0.0, deadline - time.monotonic()))
                    flags, _tr, body = unpack_envelope(data)
                    val = serialization.loads(body) \
                        if flags & FLAG_ERROR else decode_value(flags, body)
                    if flags & FLAG_ERROR:
                        first_err = first_err or val
                    else:
                        losses.append(val)
            reports: List[dict] = []
            for r in range(dp):
                for rd in self._report_readers[r]:
                    data = rd.recv(
                        timeout=max(0.0, deadline - time.monotonic()))
                    flags, _tr, body = unpack_envelope(data)
                    val = serialization.loads(body) \
                        if flags & FLAG_ERROR else decode_value(flags, body)
                    if flags & FLAG_ERROR:
                        first_err = first_err or val
                    else:
                        reports.append(val)
        except CompiledGraphClosedError:
            with self._lock:
                if self._closed_error is None:
                    self._closed_error = CompiledGraphClosedError(
                        f"pipeline engine {self._gtag}: channel peer "
                        f"closed mid-step")
            self._dump_postmortem(f"step closed mid-step: "
                                  f"{self._closed_error}")
            raise self._closed_reason() from None
        except GetTimeoutError:
            self._poisoned = GetTimeoutError(
                f"pipeline engine {self._gtag}: step timed out — "
                f"in-flight state is indeterminate; shutdown() and "
                f"rebuild")
            self._dump_postmortem(f"step timeout: {self._poisoned}")
            raise
        except BaseException as e:
            # anything else raised mid-step (a serialization failure, a
            # channel-capacity error) can leave a partial round in the
            # rings — e.g. microbatch k sent with no matching target —
            # so the next step would consume stale envelopes and pair
            # activations with the wrong targets. Not resumable.
            self._poisoned = e
            self._dump_postmortem(f"step poisoned: {e!r}")
            raise
        self.last_step_s = time.perf_counter() - t0
        _H_STEP.observe(self.last_step_s, tags={"engine": self._gtag})
        if _FLREC.enabled:
            _FLREC.record("pipeline.step.end", self._gtag,
                          {"step": self._step_count,
                           "wall_ms": round(self.last_step_s * 1e3, 3)})
        if first_err is not None:
            # envelope error propagation kept every channel count
            # aligned, but residual/grad state on the stages is gone —
            # the engine is not safely resumable after a stage raise
            self._poisoned = first_err
            self._dump_postmortem(f"stage raised: {first_err!r}")
            raise first_err
        self.last_reports = reports
        self._step_count += 1
        self._maybe_checkpoint()
        return float(sum(float(l) for l in losses) / (M * dp))

    def _check_open(self) -> None:
        if self._closed_error is not None or self._torn:
            raise self._closed_reason()
        if self._poisoned is not None:
            raise CompiledGraphError(
                f"pipeline engine {self._gtag} is poisoned by an "
                f"earlier step failure ({type(self._poisoned).__name__}"
                f": {self._poisoned}); shutdown() and rebuild")

    def _closed_reason(self) -> Exception:
        err = self._closed_error
        if err is None:
            err = CompiledGraphClosedError(
                f"pipeline engine {self._gtag} was shut down")
        return type(err)(str(err))

    # -- data feed (ray_tpu/data/feed.py; docs/DATA.md) --------------------

    def attach_feed(self, feed, timeout: float = 60.0) -> None:
        """Hand the input-producer role to a :class:`ray_tpu.data.feed.
        DataFeed`: one pump actor per dp replica writes ``(inputs,
        targets)`` microbatches straight into this engine's
        pre-allocated ``in->c0`` / ``in->targets`` rings. ``step()``
        (with no batch) then only reads losses/reports — the
        tokenize→pack→shuffle→train loop runs with zero driver
        round-trips in steady state.

        Ring slot occupancy backpressures the pumps; a pump death
        aborts the engine with :class:`DataFeedError` and ``recover()``
        re-attaches; ``detach_feed()`` hands the rings back for
        hand-feeding."""
        with self._lock:
            self._check_open()
        if self._feed is not None:
            raise CompiledGraphError(
                f"pipeline engine {self._gtag} already has a feed "
                f"attached; detach_feed() first")
        if feed.dp != self.dp:
            raise ValueError(
                f"feed is sharded {feed.dp}-wide, engine dp={self.dp}")
        self._feed = feed
        self._feed_base_step = self._step_count
        try:
            self._spawn_feed(timeout)
        except BaseException:
            self._feed = None
            raise

    def _spawn_feed(self, timeout: float) -> None:
        from ..data.feed import _FeedPump
        from ..util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy

        rt = self._rt
        local_nid = next(
            (nid for nid, n in rt.nodes.items()
             if not getattr(n, "is_remote", False)), None)
        cls = ray_tpu.remote(_FeedPump)
        actors: List[Any] = []
        setups = []
        for r in range(self.dp):
            in_spec = self._edge_specs.get(f"r{r}:in->c0")
            tgt_spec = self._edge_specs.get(f"r{r}:in->targets")
            if in_spec is None or tgt_spec is None:
                raise CompiledGraphError(
                    "input edge specs missing — engine not compiled")
            opts: Dict[str, Any] = {"num_cpus": 0.5}
            if (in_spec["kind"] == "shm" or tgt_spec["kind"] == "shm") \
                    and local_nid is not None:
                # shm input rings live on the head node by construction
                # (driver-producer edges): the pump must map the same
                # segments, so pin it there. rpc edges route through the
                # head and the pump can run anywhere.
                opts["scheduling_strategy"] = \
                    NodeAffinitySchedulingStrategy(local_nid, soft=False)
            a = cls.options(**opts).remote()
            # seq handoff: shm ledgers are segment-resident (no state to
            # pass); rpc writers continue at the driver's current seq
            setups.append(a.setup.remote(
                in_spec, tgt_spec,
                int(getattr(self._in_writers[r], "_seq", 0)),
                int(getattr(self._tgt_writers[r], "_seq", 0)),
                self.graph_id, self._feed.shard_blobs[r],
                f"{self._gtag}-r{r}"))
            actors.append(a)
        try:
            ray_tpu.get(setups, timeout=timeout)
            ray_tpu.get([a.start.remote() for a in actors],
                        timeout=timeout)
        except BaseException:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            raise
        self._feed_actors = actors
        self._feed_actor_ids = {a._actor_id.binary() for a in actors}

    def detach_feed(self, timeout: float = 30.0) -> None:
        """Stop the pump actors and hand the input rings back to the
        driver (hand-fed ``step()`` works again). Requires a DRAINED
        feed: every pump exhausted its iterator and every fed step has
        been read by ``step()`` — otherwise stale envelopes sit in the
        rings (and the stages run ahead on them), skewing every later
        hand-fed step, so an undrained detach raises instead. Drain by
        calling ``step()`` until every fed step is consumed (build the
        factory finite if you plan to detach), or abandon the feed with
        ``shutdown()``/``resize()``. rpc writer seqs resync from the
        pumps' final counts."""
        if self._feed is None:
            return
        M = self.num_microbatches
        # exhausted flips a beat after the last send lands; give the
        # pump threads a moment before declaring the feed undrained
        deadline = time.monotonic() + min(5.0, timeout)
        while True:
            stats = self.feed_stats(timeout)
            read_mb = (self._step_count - self._feed_base_step) * M
            if (all(s["exhausted"] for s in stats)
                    and all(s["sent"] == read_mb for s in stats)):
                break
            if time.monotonic() >= deadline:
                raise CompiledGraphError(
                    f"detach_feed() on an undrained feed: pumps sent "
                    f"{[s['sent'] for s in stats]} microbatches "
                    f"(exhausted={[s['exhausted'] for s in stats]}) "
                    f"but step() has read "
                    f"{self._step_count - self._feed_base_step} fed "
                    f"steps x {M}; stale in-flight envelopes would "
                    f"skew every later hand-fed step. Call step() "
                    f"until every fed step is read (make the factory "
                    f"finite), or abandon the feed via shutdown()/"
                    f"resize().")
            time.sleep(0.05)
        # clear the watch set FIRST: the kills below must not look like
        # a feed fault to _on_actor_event
        actors, self._feed_actors = self._feed_actors, []
        self._feed_actor_ids = set()
        self._feed = None
        for r, a in enumerate(actors):
            try:
                st = ray_tpu.get(a.stop.remote(), timeout=timeout)
                for w, key in ((self._in_writers[r], "in_seq"),
                               (self._tgt_writers[r], "tgt_seq")):
                    if hasattr(w, "_seq") and st.get(key) is not None:
                        w._seq = int(st[key])
            except Exception:
                pass
            try:
                ray_tpu.kill(a)
            except Exception:
                pass

    def feed_stats(self, timeout: float = 30.0) -> List[dict]:
        """Per-replica pump stats: {sent, exhausted, error, ...}."""
        if not self._feed_actors:
            return []
        return ray_tpu.get([a.stats.remote() for a in self._feed_actors],
                           timeout=timeout)

    # -- performance introspection (ray_tpu.perf, ISSUE 17) ----------------

    def _dump_postmortem(self, reason: str) -> Optional[str]:
        """Merged driver+worker flight-recorder bundle: drains this
        process's ring plus — best-effort, 5s per worker — every stage
        worker still reachable. Throttled inside dump_bundle; never
        raises (the abort being recorded takes precedence)."""
        try:
            from ..perf.postmortem import dump_bundle

            fetchers = {}
            for plan in self._actor_plans.values():
                name = f"worker:{plan.replica}.{plan.stage}"
                fetchers[name] = (
                    lambda p=plan: p.node.worker_cgraph_call(
                        p.worker, "flightrec_snapshot", {}, timeout=5.0))
            return dump_bundle(
                reason, origin="driver", ring_fetchers=fetchers,
                meta={"engine": self._gtag, "dp": self.dp,
                      "num_stages": self.num_stages,
                      "num_microbatches": self.num_microbatches,
                      "step": self._step_count, "reason": reason})
        except Exception:
            return None

    def set_flight_recording(self, on: bool) -> None:
        """Toggle the flight-recorder event stream on the driver and on
        every stage worker (best-effort, 5s per worker). The per-op perf
        counters that feed :meth:`profile` stay on either way — this
        gates only the event ring, and exists mainly so the overhead
        bench can A/B it."""
        from ..perf.recorder import set_enabled

        set_enabled(on)
        for plan in self._actor_plans.values():
            try:
                plan.node.worker_cgraph_call(
                    plan.worker, "flightrec_set_enabled", {"on": on},
                    timeout=5.0)
            except Exception:
                pass

    def profile(self, steps: int = 4, microbatches: Sequence[Any] = None,
                targets: Sequence[Any] = None,
                tokens_per_step: Optional[float] = None,
                flops_per_token: Optional[float] = None,
                peak_flops: Optional[float] = None,
                timeout: float = 300.0):
        """Run one warmup step plus ``steps`` profiled training steps
        and return a :class:`ray_tpu.perf.StepReport` with the
        per-stage exec/bubble/sync breakdown, per-op wall spans (chrome-
        trace exportable), measured bubble fraction, tokens/s and MFU.

        ``microbatches``/``targets`` default to replaying the last
        ``step()``'s inputs — profiling trains on them, exactly as
        ``step()`` would. ``tokens_per_step`` enables tokens/s;
        ``flops_per_token`` + ``peak_flops`` (default
        ``RAY_TPU_PEAK_FLOPS``) enable MFU."""
        from ..perf.report import StepReport

        if microbatches is None or targets is None:
            last = getattr(self, "_last_step_inputs", None)
            if last is None:
                raise ValueError(
                    "profile() without microbatches/targets needs at "
                    "least one prior step() to replay")
            microbatches, targets = last
        if peak_flops is None:
            peak_flops = float(os.environ.get("RAY_TPU_PEAK_FLOPS", 0))
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        # warmup step doubles as the cumulative-counter baseline: the
        # executor's exec/bubble sinks count from graph load, so the
        # profiled window is (final - baseline)
        self.step(microbatches, targets, timeout=timeout)
        base = {f"{r['dp_rank']}.{r['stage']}": dict(r.get("perf") or {})
                for r in self.last_reports}
        t_start = time.time()
        wall0 = time.perf_counter()
        step_ms: List[float] = []
        sync_acc: Dict[str, float] = {}
        upd_acc: Dict[str, float] = {}
        ops_acc: Dict[str, List[dict]] = {}
        final: Dict[str, dict] = {}
        for _ in range(steps):
            self.step(microbatches, targets, timeout=timeout)
            step_ms.append(self.last_step_s * 1e3)
            for r in self.last_reports:
                tag = f"{r['dp_rank']}.{r['stage']}"
                sync_acc[tag] = sync_acc.get(tag, 0.0) \
                    + float(r.get("sync_ms", 0.0))
                upd_acc[tag] = upd_acc.get(tag, 0.0) \
                    + float(r.get("update_ms", 0.0))
                perf = r.get("perf") or {}
                ops_acc.setdefault(tag, []).extend(perf.get("ops", ()))
                final[tag] = perf
        wall_s = time.perf_counter() - wall0
        stages = []
        for tag in sorted(final):
            b = base.get(tag, {})
            f = final[tag]
            bubble_ms = (f.get("bubble_s", 0.0)
                         - b.get("bubble_s", 0.0)) * 1e3
            stages.append({
                "stage": tag,
                "exec_ms": round((f.get("exec_s", 0.0)
                                  - b.get("exec_s", 0.0)) * 1e3, 3),
                # in this engine the 1F1B bubble IS recv-blocked time —
                # the executor times only the blocking channel read
                "bubble_ms": round(bubble_ms, 3),
                "recv_ms": round(bubble_ms, 3),
                "send_ms": round((f.get("send_s", 0.0)
                                  - b.get("send_s", 0.0)) * 1e3, 3),
                "sync_ms": round(sync_acc.get(tag, 0.0), 3),
                "update_ms": round(upd_acc.get(tag, 0.0), 3),
                "ops": ops_acc.get(tag, []),
            })
        n_inst = max(1, len(stages))
        phases = {
            "compute": round(sum(s["exec_ms"] for s in stages) / n_inst,
                             3),
            "bubble": round(sum(s["bubble_ms"] for s in stages) / n_inst,
                            3),
            "send": round(sum(s["send_ms"] for s in stages) / n_inst, 3),
        }
        tokens = float(tokens_per_step or 0.0) * steps
        events = [ev for ev in _FLREC.snapshot(clear=False)
                  if ev["ts"] >= t_start][-2000:]
        return StepReport(
            kind="pipeline", engine=self._gtag, steps=steps,
            wall_s=wall_s, step_ms=step_ms, stages=stages, phases=phases,
            tokens=tokens,
            tokens_per_s=tokens / wall_s if tokens and wall_s > 0 else 0.0,
            flops_per_token=float(flops_per_token or 0.0),
            peak_flops=peak_flops, num_stages=self.num_stages,
            num_microbatches=self.num_microbatches, events=events,
            extra={"dp": self.dp})

    def get_params(self) -> List[Any]:
        """Chunk params in GLOBAL chunk order (replica 0's copy)."""
        P, V = self.num_stages, self.virtual
        per_actor = ray_tpu.get(
            [a.get_params.remote() for a in self.actor_grid[0]],
            timeout=120)
        return [per_actor[g % P][g // P] for g in range(self.num_chunks)]

    def opt_state_bytes(self) -> List[int]:
        """Per-stage optimizer-state bytes on replica 0 (the ~1/dp
        ZeRO shrink shows up here)."""
        return ray_tpu.get(
            [a.opt_state_bytes.remote() for a in self.actor_grid[0]],
            timeout=60)

    # -- checkpoint / restore ----------------------------------------------

    def _pull_state_grid(self, timeout: float = 120.0) -> List[List[dict]]:
        """[r][i] -> stage get_state() payload, pulled over the dynamic
        path (the iterative loops are idle between steps)."""
        refs = [[a.get_state.remote() for a in row]
                for row in self.actor_grid]
        return [ray_tpu.get(row, timeout=timeout) for row in refs]

    def save_checkpoint(self, blocking: bool = False) -> str:
        """Snapshot every stage's params + optimizer state at the current
        step boundary and commit it to ``checkpoint_dir`` atomically
        (write to a temp file, ``os.replace`` into place, then replace
        the LATEST pointer). The state pull is synchronous — it must see
        a step boundary — but the serialization + disk IO runs on a
        background thread unless ``blocking``. Returns the target path
        (readable once committed; ``wait_for_checkpoints()`` joins)."""
        if not self.checkpoint_dir:
            raise ValueError(
                "save_checkpoint() needs checkpoint_dir= at construction")
        with self._lock:
            self._check_open()
        step = self._step_count
        states = self._pull_state_grid()
        path = os.path.join(self.checkpoint_dir, f"ckpt-{step:08d}.pkl")
        payload = {
            "step": step,
            "engine": self._engine_meta(),
            "states": states,
        }

        def _write() -> None:
            tmp = path + f".tmp.{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    cloudpickle.dump(payload, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)  # rename-commit: readers never
                # observe a torn checkpoint
                with self._ckpt_lock:
                    # concurrent writer threads can finish out of order
                    # (a large step-N pickle outliving step-N+1's): only
                    # advance LATEST, never roll it back to an older step
                    if step < self._latest_step:
                        return
                    latest_tmp = os.path.join(
                        self.checkpoint_dir, f"LATEST.tmp.{os.getpid()}")
                    with open(latest_tmp, "w") as f:
                        f.write(os.path.basename(path))
                    os.replace(latest_tmp,
                               os.path.join(self.checkpoint_dir,
                                            "LATEST"))
                    self._latest_step = step
                    self.last_checkpoint_path = path
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass

        if blocking:
            _write()
        else:
            t = threading.Thread(target=_write, daemon=True,
                                 name=f"pipeline-ckpt-{self._gtag}")
            with self._ckpt_lock:
                self._ckpt_pending = [
                    p for p in self._ckpt_pending if p.is_alive()]
                self._ckpt_pending.append(t)
            t.start()
        return path

    def wait_for_checkpoints(self, timeout: float = 60.0) -> None:
        """Join every in-flight async checkpoint write."""
        with self._ckpt_lock:
            pending = list(self._ckpt_pending)
        deadline = time.monotonic() + timeout
        for t in pending:
            t.join(max(0.0, deadline - time.monotonic()))

    def _engine_meta(self) -> dict:
        return {"num_chunks": self.num_chunks,
                "num_stages": self.num_stages,
                "virtual": self.virtual, "dp": self.dp,
                "fsdp": self.fsdp,
                "zero_update": self.zero_update,
                "grad_codec": self.grad_codec,
                "num_microbatches": self.num_microbatches}

    def _maybe_checkpoint(self) -> None:
        if self.checkpoint_dir and self.checkpoint_every > 0 \
                and self._step_count % self.checkpoint_every == 0:
            self.save_checkpoint()

    @staticmethod
    def latest_checkpoint(checkpoint_dir: str) -> Optional[str]:
        """Newest committed checkpoint path in a directory (via the
        LATEST pointer; falls back to a name scan)."""
        ptr = os.path.join(checkpoint_dir, "LATEST")
        try:
            with open(ptr) as f:
                path = os.path.join(checkpoint_dir, f.read().strip())
            if os.path.exists(path):
                return path
        except OSError:
            pass
        cands = sorted(
            n for n in (os.listdir(checkpoint_dir)
                        if os.path.isdir(checkpoint_dir) else ())
            if n.startswith("ckpt-") and n.endswith(".pkl"))
        return os.path.join(checkpoint_dir, cands[-1]) if cands else None

    @staticmethod
    def load_checkpoint(path: str) -> dict:
        with open(path, "rb") as f:
            return cloudpickle.load(f)

    def restore(self, checkpoint: str) -> int:
        """Load a committed checkpoint into the LIVE engine (fresh-build
        restart path): every stage's params + optimizer state replace the
        current ones at the next step boundary. Returns the restored
        step count. ``recover()`` is the respawn-then-restore path for an
        engine whose stages died."""
        ckpt = self.load_checkpoint(checkpoint)
        self._check_ckpt_shape(ckpt)
        with self._lock:
            self._check_open()
        loads = []
        for r in range(self.dp):
            for i in range(self.num_stages):
                st = ckpt["states"][r][i]
                loads.append(self.actor_grid[r][i].load_state.remote(
                    st["params"], st["opt"], st["kind"]))
        ray_tpu.get(loads, timeout=self._setup_timeout)
        self._step_count = int(ckpt["step"])
        return self._step_count

    def _check_ckpt_shape(self, ckpt: dict) -> None:
        want = {"num_chunks": self.num_chunks, "virtual": self.virtual,
                "dp": self.dp, "fsdp": self.fsdp,
                "zero_update": self.zero_update}
        # fsdp joined the payload later: checkpoints written before it
        # are unsharded by construction, so default the key to 1 rather
        # than rejecting a compatible restore
        have = {k: ckpt.get("engine", {}).get(k, 1 if k == "fsdp" else None)
                for k in want}
        if have != want:
            raise ValueError(
                f"checkpoint shape {have} does not match engine {want}")

    # -- recovery ----------------------------------------------------------

    def recover(self, checkpoint: Optional[str] = None,
                timeout: float = 120.0) -> int:
        """Bring a faulted engine back: tear down whatever channels are
        left (idempotent — a stage-death abort already did most of it),
        kill and respawn EVERY stage actor (survivors hold residual/grad
        state from the aborted step and must not leak it into the resumed
        trajectory), recompile channels under a fresh graph id, and
        restore from ``checkpoint`` (default: the newest commit in
        checkpoint_dir, else a step-0 restart from the construction-time
        params). Returns the step count training resumes from.

        The resumed loss trajectory is bit-identical to a clean restart
        from the same checkpoint: both paths run the same jitted programs
        over the same restored arrays (test_pipeline_cgraph asserts
        this)."""
        deadline = time.monotonic() + timeout
        self.wait_for_checkpoints()
        # serialize against an in-flight abort teardown, then reset
        self.teardown()
        ckpt_path = checkpoint
        if ckpt_path is None and self.checkpoint_dir:
            ckpt_path = self.latest_checkpoint(self.checkpoint_dir)
        state_grid = None
        step = 0
        if ckpt_path is not None:
            ckpt = self.load_checkpoint(ckpt_path)
            if int(ckpt.get("engine", {}).get("dp", self.dp)) != self.dp:
                # the newest commit predates a resize: re-shard it to
                # the engine's current width (bit-exact byte movement)
                ckpt = reshard_checkpoint(ckpt, self.dp)
            self._check_ckpt_shape(ckpt)
            state_grid = ckpt["states"]
            step = int(ckpt["step"])
        self._kill_stages_and_wait(deadline, "recover()")
        self._destroy_collective_groups()
        self._drop_pg_if_degraded()
        self._reset_graph_state()
        self._spawn_actors(self._init_params,
                           per_actor_state=state_grid)
        self._compile()
        if self._feed is not None:
            # re-attach: fresh pump actors over the recompiled rings.
            # The shard factories restart their iterators — the resumed
            # trajectory replays from the restored checkpoint exactly
            # like a clean restart would.
            self._spawn_feed(
                max(1.0, min(60.0, deadline - time.monotonic())))
            # pump iterators restarted from scratch: fed-step drain
            # accounting (detach_feed) restarts with them
            self._feed_base_step = step
        self._step_count = step
        return step

    def _drop_pg_if_degraded(self) -> None:
        """A bundle whose node died (or is draining toward a preemption
        deadline) would strand the respawn — actor creations against a
        dead bundle park forever. Drop the group so the respawn sizes a
        fresh one over the nodes that remain."""
        if self._pg is None:
            return
        degraded = True
        try:
            info = self._rt.gcs.get_pg(self._pg.id)
            if info is not None:
                degraded = False
                for nid in info.bundle_nodes:
                    node = self._rt.nodes.get(nid) if nid else None
                    if node is None or not node.alive \
                            or getattr(node, "draining", False):
                        degraded = True
                        break
        except Exception:
            pass
        if degraded:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None

    def _destroy_collective_groups(self) -> None:
        """Kill the dp collective groups' detached rendezvous store
        actors from the DRIVER (named ``rtpu_collective:<group>:<dp>``).
        recover()/resize() kill the stage actors without a cleanup()
        hop, so the stores would otherwise leak — and a store stranded
        on a draining node keeps it 'busy' forever, blocking the clean
        preemption exit. Must run while the OLD gtag/dp are current."""
        if self.dp <= 1 or self._tx_blob is None:
            return
        for i in range(self.num_stages):
            name = f"rtpu_collective:zpipe-{self._gtag}-s{i}:{self.dp}"
            try:
                ray_tpu.kill(ray_tpu.get_actor(name))
            except Exception:
                pass

    def _kill_stages_and_wait(self, deadline: float, what: str) -> None:
        """Kill every stage actor (dead ones no-op) and wait for the
        records to reach DEAD so placement slots free up for a respawn."""
        for a in getattr(self, "actors", []):
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for a in getattr(self, "actors", []):
            while self._rt.actor_state(a._actor_id) not in ("DEAD",):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"stage actor {a._actor_id.hex()[:8]} did not "
                        f"reach DEAD during {what}")
                time.sleep(0.05)

    def _reset_graph_state(self) -> None:
        """Reset engine plumbing for a fresh compile (recover/resize)."""
        with self._lock:
            self._torn = False
            self._poisoned = None
            self._closed_error = None
        self._stop = threading.Event()
        self.graph_id = os.urandom(16)
        self._gtag = self.graph_id.hex()[:8]
        self._actor_plans = {}
        self._alloc = []
        self._in_writers = []
        self._tgt_writers = []
        self._loss_readers = []
        self._report_readers = []
        self._qreaders = {}
        self._edge_specs = {}
        self._feed_actors = []
        self._feed_actor_ids = set()
        self._unsub = None
        self._shutdown_done = False

    # -- elastic capacity (docs/FAULT_TOLERANCE.md "Elasticity") -----------

    def resize(self, dp: int, timeout: float = 300.0,
               scheduling_strategies: Optional[Sequence] = None) -> int:
        """Change the engine's data-parallel width IN PLACE, between
        steps: drain is implicit (the caller is between step() calls),
        state is pulled at the step boundary, ZeRO optimizer shards
        re-split across the new width (``reshard_checkpoint`` — pure
        byte movement, bit-exact), every stage actor respawns into
        freshly-sized placement bundles (draining nodes excluded by the
        scheduler), channels recompile under a fresh graph id, and
        training resumes at the SAME step count and global batch:
        ``num_microbatches`` rescales so dp * M is invariant, and the
        resumed trajectory is bit-identical to a fixed-size run at the
        new width restored from the same (resharded) checkpoint.

        Returns the step count training resumes from. The new width must
        divide the global microbatch count; engines built with explicit
        ``scheduling_strategies`` must pass a new P*dp-sized list."""
        new_dp = int(dp)
        if new_dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        if new_dp == self.dp:
            return self._step_count
        total_mb = self.num_microbatches * self.dp
        if total_mb % new_dp:
            raise ValueError(
                f"global batch of {total_mb} microbatches does not "
                f"divide across dp={new_dp}")
        if self._strategies is not None and scheduling_strategies is None:
            raise CompiledGraphError(
                "engine was built with explicit scheduling_strategies; "
                f"resize(dp={new_dp}) needs a new "
                f"{self.num_stages * new_dp}-entry list")
        with self._lock:
            self._check_open()
        t0 = time.perf_counter()
        deadline = time.monotonic() + timeout
        direction = "grow" if new_dp > self.dp else "shrink"
        if self._feed is not None:
            # a feed is sharded at the OLD width — a resize invalidates
            # the sharding, so the feed is dropped (teardown kills the
            # pumps); callers re-attach a freshly split feed after
            self._feed = None
        self.wait_for_checkpoints()
        states = self._pull_state_grid()
        resharded = reshard_checkpoint(
            {"step": self._step_count, "engine": self._engine_meta(),
             "states": states}, new_dp)
        self.teardown()
        self._kill_stages_and_wait(deadline, f"resize(dp={new_dp})")
        self._destroy_collective_groups()
        if self._pg is not None:
            # bundle count changes with dp: drop the old group so the
            # respawn sizes a fresh one (and lands off draining nodes)
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
        if scheduling_strategies is not None:
            self._strategies = list(scheduling_strategies)
        self._reset_graph_state()
        self.dp = new_dp
        self.num_microbatches = total_mb // new_dp
        self._spawn_actors(self._init_params,
                           per_actor_state=resharded["states"])
        self._compile()
        _H_RESIZE.observe(time.perf_counter() - t0,
                          tags={"direction": direction})
        return self._step_count

    def enable_elastic(self, *, min_dp: int = 1,
                       max_dp: Optional[int] = None,
                       grow_on_join: bool = True) -> None:
        """Hands-off elasticity: subscribe to the GCS "node" channel and
        ride capacity changes without operator intervention
        (ROADMAP item 4 / docs/FAULT_TOLERANCE.md "Elasticity").

        - ``NODE_PREEMPTING`` (a provider preemption notice, or a chaos
          ``preempt=`` schedule) for a node hosting any of this engine's
          stage actors ⇒ the next ``step()`` first shrinks dp below the
          doomed rows — *shrink before the axe*. If no valid smaller
          width exists the notice is ignored and an early kill falls
          back to the ``recover()`` path.
        - a node joining (``ALIVE``) with ``grow_on_join`` ⇒ the next
          ``step()`` grows dp to the next valid width up to ``max_dp``
          (default: the CURRENT width — "grow back to where I started"
          after preemption shrinks; pass a larger cap to scale beyond).

        The resize itself runs inside ``step()`` — at a step boundary by
        construction — so callers keep feeding the same dp*M global
        batch and never see the width change beyond a slower step."""
        if getattr(self, "_elastic_unsub", None) is not None:
            return
        # grow_on_join without an explicit cap grows back to the width
        # the engine had when elasticity was enabled — a silent
        # never-grow default would contradict the flag
        cap = int(max_dp) if max_dp \
            else (self.dp if grow_on_join else None)
        self._elastic = {"min": max(1, int(min_dp)),
                         "max": cap,
                         "grow": bool(grow_on_join)}
        with self._lock:  # the pubsub callback below reads it locked
            self._pending_dp: Optional[int] = None
        self._elastic_unsub = self._rt.gcs.pubsub.subscribe(
            "node", self._on_elastic_node_event)

    def _valid_widths(self) -> List[int]:
        total_mb = self.num_microbatches * self.dp
        return [d for d in range(1, total_mb + 1) if total_mb % d == 0]

    def _on_elastic_node_event(self, msg) -> None:
        try:
            state, node_id = msg[0], msg[1]
        except Exception:
            return
        cfg = getattr(self, "_elastic", None)
        if cfg is None:
            return
        if state == "PREEMPTING":
            plans = getattr(self, "_plans", None)
            if not plans:
                return
            n_on_node = sum(1 for row in plans for p in row
                            if p.node.node_id == node_id)
            if n_on_node == 0:
                return
            # the resize respawns EVERY stage off the draining node, so
            # the question is only how much total capacity to give back:
            # at least the doomed node's share, rounded up to whole rows
            import math

            doomed = max(1, math.ceil(n_on_node / self.num_stages))
            floor = cfg["min"]
            with self._lock:
                pending = getattr(self, "_pending_dp", None)
                # two nodes doomed in the same window: the second notice
                # shrinks from the already-queued target, not from the
                # current width — give-backs accumulate
                base = pending if pending is not None \
                    and pending < self.dp else self.dp
                cands = [d for d in self._valid_widths()
                         if floor <= d <= base - doomed]
                if not cands:
                    # can't give back that much: shrink as far as widths
                    # allow; at the floor already, the axe + recover()
                    # is the fallback (the notice/SIGKILL race test)
                    cands = [d for d in self._valid_widths()
                             if floor <= d < base]
                if not cands:
                    return
                self._pending_dp = max(cands)
        elif state == "ALIVE" and cfg["grow"]:
            cap = cfg["max"]
            if cap is None:
                return
            with self._lock:
                pending = getattr(self, "_pending_dp", None)
            base = pending if pending is not None else self.dp
            if base >= cap:
                return
            cands = [d for d in self._valid_widths() if base < d <= cap]
            if not cands:
                return
            target = min(cands)
            with self._lock:
                if pending is not None and pending < self.dp:
                    # a shrink is queued for a doomed node: it must land
                    # first — remember the grow and apply it right after
                    self._regrow_dp = target
                else:
                    self._pending_dp = target

    def _grow_feasible(self, dp_new: int) -> bool:
        """Cheap placement pre-check before a grow: the respawn kills
        the current actors first (freeing their CPU), then needs
        P * dp_new bundles — refuse the grow when the non-draining
        cluster clearly cannot hold it, rather than tearing the engine
        down into a placement timeout."""
        try:
            res = dict(self._res or {"CPU": 1.0})
            per = float(res.get("CPU", 1.0))
            need = per * self.num_stages * dp_new
            avail = sum(float(v.available.get("CPU", 0.0))
                        for v in self._rt._views())
            freed = per * self.num_stages * self.dp
            return avail + freed >= need
        except Exception:
            return True

    def _apply_pending_resize(self) -> None:
        with self._lock:
            pending, self._pending_dp = getattr(self, "_pending_dp",
                                                None), None
        if pending is not None and pending != self.dp:
            if pending > self.dp and not self._grow_feasible(pending):
                pending = None  # capacity shrank again since the event
            else:
                self.resize(pending)
        with self._lock:
            regrow = getattr(self, "_regrow_dp", None)
            self._regrow_dp = None
            if regrow is not None and regrow != self.dp \
                    and self._pending_dp is None:
                self._pending_dp = regrow  # lands at the NEXT boundary

    def _deliver(self, cid: str, seq: int, data: bytes) -> None:
        q = self._qreaders.get(cid)
        if q is not None:
            q.deliver(seq, data)

    def _on_actor_event(self, msg) -> None:
        try:
            actor_id, state = msg
        except Exception:
            return
        from ..core.gcs import ActorState

        if state != ActorState.DEAD:
            return
        key = actor_id.binary() if hasattr(actor_id, "binary") else None
        if key in self._actor_plans and not self._torn:
            self._abort(CompiledGraphClosedError(
                f"pipeline engine {self._gtag}: stage actor "
                f"{actor_id.hex()[:8]} died while the engine was live"))
        elif key in self._feed_actor_ids and not self._torn:
            # feed pumps are a stateless tier, but a dead pump leaves
            # the input rings starved mid-round — typed error so the
            # caller knows recover() (which re-attaches) is the fix
            self._abort(DataFeedError(
                f"pipeline engine {self._gtag}: data-feed pump "
                f"{actor_id.hex()[:8]} died while the engine was live; "
                f"recover() respawns the stages and re-attaches the "
                f"feed"))

    def _abort(self, err: Exception) -> None:
        with self._lock:
            if self._closed_error is None:
                self._closed_error = err
        # unblock any in-flight step() NOW (driver endpoints poll this),
        # and run the teardown off-thread: this is called from the GCS
        # pubsub callback, and blocking control-plane calls made from
        # that thread can't be serviced until the callback returns
        self._stop.set()

        def _dump_and_teardown():
            # ring fetch is a blocking control-plane call — it can only
            # run here, never in the pubsub callback itself
            self._dump_postmortem(f"abort: {err!r}")
            self.teardown()

        threading.Thread(target=_dump_and_teardown, daemon=True,
                         name=f"pipeline-abort-{self._gtag}").start()

    def teardown(self) -> None:
        """Stop the resident loops and release every channel segment
        (leak-asserted in tests); actors stay alive. Idempotent; a
        second caller blocks until the first finishes releasing."""
        with self._teardown_lock:
            self._teardown_locked()

    def _teardown_locked(self) -> None:
        with self._lock:
            if self._torn:
                return
            self._torn = True
            if self._closed_error is None:
                self._closed_error = CompiledGraphClosedError(
                    f"pipeline engine {self._gtag} was shut down")
        self._stop.set()
        if self._unsub is not None:
            try:
                self._unsub()
            except Exception:
                pass
        # feed pumps go first: clear the watch set (their deaths must
        # not re-abort), then kill — blocked sends unwedge when the
        # ring ledgers are poisoned below. The feed DESCRIPTOR stays:
        # recover() re-attaches from it.
        feed_actors, self._feed_actors = self._feed_actors, []
        self._feed_actor_ids = set()
        for a in feed_actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        endpoints = (self._in_writers + self._tgt_writers
                     + self._loss_readers
                     + [rd for row in self._report_readers for rd in row])
        for ch in endpoints:
            try:
                ch.mark_closed()
            except Exception:
                pass
        for plan in self._actor_plans.values():
            try:
                plan.node.worker_cgraph_call(
                    plan.worker, "cgraph_stop",
                    {"graph_id": self.graph_id}, timeout=10.0)
            except Exception:
                pass
        for ch in endpoints:
            try:
                ch.close()
            except Exception:
                pass
        for node, cid in self._alloc:
            try:
                if getattr(node, "is_remote", False):
                    node.channel.call("cgraph_release_channel",
                                      {"cid": cid}, timeout=10)
                else:
                    node.store.release_channel(cid)
            except Exception:
                pass
        self._alloc = []
        try:
            self._rt._cgraph_unregister(self)
        except Exception:
            pass

    def shutdown(self) -> None:
        """Full teardown: stop loops, release channels, destroy dp
        collective groups, kill the stage actors, drop the placement
        group. Idempotent under double-invocation (atexit + signal
        handler + explicit call); a reentrant call returns once teardown
        marked the engine torn."""
        self.teardown()
        unsub = getattr(self, "_elastic_unsub", None)
        if unsub is not None:
            self._elastic_unsub = None
            try:
                unsub()
            except Exception:
                pass
        try:
            self.wait_for_checkpoints(timeout=30.0)
        except Exception:
            pass
        with self._ckpt_lock:
            if self._shutdown_done:
                return
            self._shutdown_done = True
        if self.dp > 1 and getattr(self, "actor_grid", None):
            try:
                ray_tpu.get(
                    [row[i].cleanup.remote()
                     for row in self.actor_grid[:1]
                     for i in range(len(row))], timeout=30)
            except Exception:
                pass
            # backstop: when the stages are already dead (post-abort
            # shutdown) the cleanup hop failed — kill the rendezvous
            # stores from the driver so nothing detached leaks
            self._destroy_collective_groups()
        for a in getattr(self, "actors", []):
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass

    def __del__(self):
        try:
            if not self._torn:
                self.teardown()
        except Exception:
            pass
