"""Trainers.

Parity with the reference's Train API (ref: python/ray/train/
base_trainer.py:570 fit; data_parallel_trainer.py:432 training_loop
driving BackendExecutor over a WorkerGroup; torch/torch_trainer.py:16).
`JaxTrainer` is the native trainer (mesh backend); `DataParallelTrainer`
is the generic base; failure handling = gang restart from the latest
checkpoint (ref: FailureConfig semantics, tune/execution/experiment_state).
"""
from __future__ import annotations

import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from .backend_executor import BackendExecutor, TrainWorkerError
from .checkpoint import Checkpoint, prune_checkpoints
from .config import (CheckpointConfig, FailureConfig, Result, RunConfig,
                     ScalingConfig)


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on a gang of workers, streams results,
    persists rank-0 checkpoints, restarts the gang on worker failure."""

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.train_loop = train_loop_per_worker
        self.train_config = dict(train_loop_config or {})
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = dict(datasets or {})
        self.resume_checkpoint = resume_from_checkpoint

    # -- dataset sharding ----------------------------------------------------

    def _dataset_shards(self) -> Optional[List[dict]]:
        if not self.datasets:
            return None
        from ..data import DataShard, DatasetPipeline
        from ..data.iterator import Shardable

        n = self.scaling.num_workers
        shards: List[dict] = [{} for _ in range(n)]
        for name, ds in self.datasets.items():
            if isinstance(ds, Shardable):
                # the DataShard contract: exactly n shards, rows
                # disjoint and exhaustive (enforced here so a broken
                # implementer fails loudly, not with silently skewed
                # or duplicated per-rank data)
                parts = ds.split_shards(n)
                if len(parts) != n or not all(
                        isinstance(p, DataShard) for p in parts):
                    raise TypeError(
                        f"dataset {name!r}: split_shards({n}) must "
                        f"return exactly {n} DataShards (the Shardable "
                        f"contract); got {len(parts)} x "
                        f"{[type(p).__name__ for p in parts[:3]]}")
            elif isinstance(ds, DatasetPipeline):
                parts = ds.split(n)
            elif isinstance(ds, (list, tuple)):
                parts = [list(ds[i::n]) for i in range(n)]
            else:
                parts = [ds] * n
            for i in range(n):
                shards[i][name] = parts[i]
        return shards

    # -- the controller loop -------------------------------------------------

    def fit(self) -> Result:
        path = self.run_config.resolved_storage_path()
        os.makedirs(path, exist_ok=True)
        max_failures = self.run_config.failure_config.max_failures
        ckpt_cfg = self.run_config.checkpoint_config
        failures = 0
        latest_ckpt = self.resume_checkpoint
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        error: Optional[BaseException] = None

        while True:
            executor = BackendExecutor(
                self.scaling, experiment_name=self.run_config.name or "train")
            try:
                executor.start(self.train_loop, self.train_config,
                               dataset_shards=self._dataset_shards(),
                               checkpoint=latest_ckpt)
                while True:
                    results = executor.next_results()
                    if results is None:
                        break
                    rank0 = results[0]
                    last_metrics = dict(rank0["metrics"])
                    last_metrics["iteration"] = rank0["iteration"]
                    history.append(last_metrics)
                    if rank0.get("checkpoint") is not None:
                        latest_ckpt = rank0["checkpoint"]
                        ckpt_dir = os.path.join(
                            path, f"checkpoint_{rank0['iteration']:06d}")
                        latest_ckpt.to_directory(ckpt_dir)
                        latest_ckpt = Checkpoint.from_directory(ckpt_dir)
                        prune_checkpoints(path, ckpt_cfg.num_to_keep)
                break  # clean finish
            except TrainWorkerError as e:
                failures += 1
                if max_failures >= 0 and failures > max_failures:
                    error = e
                    break
                time.sleep(0.2)  # gang restart backoff
            except Exception as e:  # noqa: BLE001 — surface in Result
                error = e
                traceback.print_exc()
                break
            finally:
                executor.shutdown()

        return Result(metrics=last_metrics, checkpoint=latest_ckpt,
                      path=path, error=error, metrics_history=history)


class JaxTrainer(DataParallelTrainer):
    """The native trainer: gang of workers, each with a mesh slice
    (ScalingConfig.mesh), bf16 SPMD via pjit inside the user loop.
    North-star config: GPT-2 on a v5e pod (BASELINE.md)."""


class TorchTrainer(DataParallelTrainer):
    """Torch data-parallel trainer with a REAL gloo process group (ref:
    torch/torch_trainer.py:16 + torch/config.py _setup_torch_process_group).
    Every gang worker joins `dist.init_process_group("gloo")` over a
    rank-0 TCP rendezvous before the user loop runs, so
    `train.torch.prepare_model(model)` returns a genuine
    DistributedDataParallel whose gradients allreduce across workers.
    torch-cpu only by design — the TPU compute path is JaxTrainer."""

    def fit(self) -> Result:
        import uuid

        # route_host: where the cluster's control plane listens — rank 0
        # derives ITS OWN reachable interface toward it, then advertises
        # the TCPStore address through a named broker actor (the store
        # lives in the rank-0 worker process, not on the driver)
        route_host = "127.0.0.1"
        from ..core import runtime as runtime_mod

        rt = runtime_mod.maybe_runtime()
        srv = getattr(rt, "_remote_server", None)
        if srv is not None:
            route_host = srv.address[0]
        user_loop = self.train_loop
        user_config = self.train_config

        def wrapped(config):
            from . import get_context
            from .torch_backend import (rendezvous,
                                        setup_torch_process_group,
                                        teardown_torch_process_group)

            config = dict(config)
            rdzv_name = config.pop("_torch_rdzv_name")
            rhost = config.pop("_torch_route_host")
            ctx = get_context()
            init_method = rendezvous(rdzv_name, rhost,
                                     ctx.get_world_rank(),
                                     ctx.get_world_size())
            setup_torch_process_group(init_method, ctx.get_world_rank(),
                                      ctx.get_world_size())
            try:
                return user_loop(config)
            finally:
                teardown_torch_process_group()

        self.train_loop = wrapped
        self.train_config = {**self.train_config,
                             "_torch_rdzv_name":
                                 f"_torch_rdzv_{uuid.uuid4().hex[:12]}",
                             "_torch_route_host": route_host}
        try:
            return super().fit()
        finally:
            self.train_loop = user_loop
            self.train_config = user_config
