"""gRPC ingress for Serve.

ref: python/ray/serve/_private/grpc_util.py + proxy.py gRPC path (the
reference's gRPC ingress registers user-supplied servicer functions).
Here the service is schema-generic — a GenericRpcHandler routes by
method path, so no protoc codegen is required on either side:

    method  /ray_tpu.serve/<deployment>          unary JSON -> JSON
    method  /ray_tpu.serve/<deployment>/stream   unary JSON -> stream of
                                                 JSON messages
    method  /ray_tpu.serve/_routes               deployment listing

Request/response bodies are UTF-8 JSON bytes (the wire contract the
HTTP ingress exposes, over gRPC framing — HTTP/2 multiplexing,
deadlines, and streaming flow control come from gRPC itself). Multiplex
routing rides gRPC metadata: ("model_id", ...).
"""
from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
from typing import Dict

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError

_PREFIX = "/ray_tpu.serve/"


class GrpcProxy:
    """Actor hosting the gRPC server (thread-pool execution model: each
    RPC runs a blocking DeploymentHandle call off the event loop)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 32):
        import grpc

        self._host = host
        self._handles: Dict[str, object] = {}
        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method
                if not method.startswith(_PREFIX):
                    return None
                target = method[len(_PREFIX):]
                if target == "_routes":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._routes_rpc)
                if target.endswith("/stream"):
                    name = target[:-len("/stream")]
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._make_stream_rpc(name))
                return grpc.unary_unary_rpc_method_handler(
                    proxy._make_unary_rpc(target))

        self._server = grpc.server(
            ThreadPoolExecutor(max_workers, thread_name_prefix="serve-grpc"))
        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        if self._port == 0:
            raise RuntimeError(f"could not bind gRPC ingress on "
                               f"{host}:{port}")
        self._server.start()

    # -- RPC implementations -------------------------------------------------

    def _get_handle(self, name: str):
        from .handle import DeploymentHandle

        h = self._handles.get(name)
        if h is None:
            h = self._handles[name] = DeploymentHandle(name)
        return h

    @staticmethod
    def _payload(request: bytes):
        return json.loads(request) if request else None

    @staticmethod
    def _mux_id(context) -> str:
        for k, v in context.invocation_metadata():
            if k == "model_id":
                return v
        return ""

    def _make_unary_rpc(self, name: str):
        import grpc

        def rpc(request: bytes, context) -> bytes:
            try:
                h = self._get_handle(name)
                mux = self._mux_id(context)
                if mux:
                    h = h.options(multiplexed_model_id=mux)
                # honor the CLIENT's gRPC deadline (capped so an
                # abandoned no-deadline call can't pin a pool thread
                # forever)
                remaining = context.time_remaining()
                timeout = min(remaining, 600.0) if remaining else 60.0
                result = ray_tpu.get(h.remote(self._payload(request)),
                                     timeout=timeout)
                return json.dumps(_jsonable(result)).encode()
            except Exception as e:  # noqa: BLE001 — surfaced as INTERNAL
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

        return rpc

    def _make_stream_rpc(self, name: str):
        import grpc

        def rpc(request: bytes, context):
            try:
                gen = self._get_handle(name).options(
                    stream=True,
                    multiplexed_model_id=self._mux_id(context)
                ).remote(self._payload(request))
                while True:
                    # per-item cap, same rationale as the unary path: a
                    # hung replica must not pin this thread forever
                    try:
                        item = gen.next(timeout=600.0)
                    except StopIteration:
                        break
                    yield json.dumps(_jsonable(item)).encode()
            except GetTimeoutError as e:
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                              f"stream item timed out: {e}")
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

        return rpc

    def _routes_rpc(self, request: bytes, context) -> bytes:
        import grpc

        try:
            from .controller import CONTROLLER_NAME

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            routes = ray_tpu.get(controller.list_deployments.remote(),
                                 timeout=10)
            return json.dumps({"deployments": routes}).encode()
        except Exception as e:  # noqa: BLE001 — same mapping as the
            # unary/stream handlers: INTERNAL + "TypeName: msg"
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")

    # -- actor surface -------------------------------------------------------

    def address(self) -> tuple:
        return (self._host, self._port)

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> bool:
        # stop() returns an event; wait it out so in-flight RPCs drain
        # before the caller kills this actor
        self._server.stop(grace=1.0).wait()
        return True


from .http_asyncio import _jsonable  # noqa: E402 — single shared coercion


def grpc_call(address: tuple, deployment: str, payload=None,
              model_id: str = "", timeout: float = 60.0):
    """Client helper (also shows the wire contract for non-Python
    clients): unary JSON call to a deployment."""
    import grpc

    with grpc.insecure_channel(f"{address[0]}:{address[1]}") as chan:
        fn = chan.unary_unary(
            f"{_PREFIX}{deployment}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        md = (("model_id", model_id),) if model_id else None
        out = fn(json.dumps(payload).encode(), metadata=md,
                 timeout=timeout)
        return json.loads(out)


def grpc_stream(address: tuple, deployment: str, payload=None,
                timeout: float = 60.0):
    """Client helper: streaming call yielding parsed JSON messages."""
    import grpc

    with grpc.insecure_channel(f"{address[0]}:{address[1]}") as chan:
        fn = chan.unary_stream(
            f"{_PREFIX}{deployment}/stream",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        for msg in fn(json.dumps(payload).encode(), timeout=timeout):
            yield json.loads(msg)
