"""gRPC ingress for Serve.

ref: python/ray/serve/_private/grpc_util.py + proxy.py gRPC path (the
reference's gRPC ingress registers user-supplied servicer functions).
Here the service is schema-generic — a GenericRpcHandler routes by
method path, so no protoc codegen is required on either side:

    method  /ray_tpu.serve/<deployment>          unary JSON -> JSON
    method  /ray_tpu.serve/<deployment>/stream   unary JSON -> stream of
                                                 JSON messages
    method  /ray_tpu.serve/_routes               deployment listing

Request/response bodies are UTF-8 JSON bytes (the wire contract the
HTTP ingress exposes, over gRPC framing — HTTP/2 multiplexing,
deadlines, and streaming flow control come from gRPC itself). Multiplex
routing rides gRPC metadata: ("model_id", ...).
"""
from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
from typing import Dict

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError

_PREFIX = "/ray_tpu.serve/"
# typed v1 contract (serve.proto; codegen-able by external clients)
_TYPED_PREFIX = "/ray_tpu.serve.v1.ServeAPI/"
_CONTRACT_VERSION = 1


class GrpcProxy:
    """Actor hosting the gRPC server (thread-pool execution model: each
    RPC runs a blocking DeploymentHandle call off the event loop)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 32):
        import grpc

        self._host = host
        self._handles: Dict[str, object] = {}
        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method
                if method.startswith(_TYPED_PREFIX):
                    rpc = method[len(_TYPED_PREFIX):]
                    if rpc == "Predict":
                        return grpc.unary_unary_rpc_method_handler(
                            proxy._typed_predict)
                    if rpc == "PredictStream":
                        return grpc.unary_stream_rpc_method_handler(
                            proxy._typed_predict_stream)
                    return None
                if not method.startswith(_PREFIX):
                    return None
                target = method[len(_PREFIX):]
                if target == "_routes":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._routes_rpc)
                if target.endswith("/stream"):
                    name = target[:-len("/stream")]
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._make_stream_rpc(name))
                return grpc.unary_unary_rpc_method_handler(
                    proxy._make_unary_rpc(target))

        self._server = grpc.server(
            ThreadPoolExecutor(max_workers, thread_name_prefix="serve-grpc"))
        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        if self._port == 0:
            raise RuntimeError(f"could not bind gRPC ingress on "
                               f"{host}:{port}")
        self._server.start()

    # -- RPC implementations -------------------------------------------------

    def _get_handle(self, name: str):
        from .handle import DeploymentHandle

        h = self._handles.get(name)
        if h is None:
            h = self._handles[name] = DeploymentHandle(name)
        return h

    @staticmethod
    def _payload(request: bytes):
        return json.loads(request) if request else None

    @staticmethod
    def _mux_id(context) -> str:
        for k, v in context.invocation_metadata():
            if k == "model_id":
                return v
        return ""

    def _make_unary_rpc(self, name: str):
        import grpc

        def rpc(request: bytes, context) -> bytes:
            try:
                h = self._get_handle(name)
                mux = self._mux_id(context)
                if mux:
                    h = h.options(multiplexed_model_id=mux)
                # honor the CLIENT's gRPC deadline (capped so an
                # abandoned no-deadline call can't pin a pool thread
                # forever)
                result = ray_tpu.get(h.remote(self._payload(request)),
                                     timeout=self._deadline(context))
                return json.dumps(_jsonable(result)).encode()
            except Exception as e:  # noqa: BLE001 — surfaced as INTERNAL
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

        return rpc

    def _make_stream_rpc(self, name: str):
        import grpc

        def rpc(request: bytes, context):
            try:
                gen = self._get_handle(name).options(
                    stream=True,
                    multiplexed_model_id=self._mux_id(context)
                ).remote(self._payload(request))
                while True:
                    # per-item cap, same rationale as the unary path: a
                    # hung replica must not pin this thread forever
                    try:
                        item = gen.next(timeout=600.0)
                    except StopIteration:
                        break
                    yield json.dumps(_jsonable(item)).encode()
            except GetTimeoutError as e:
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                              f"stream item timed out: {e}")
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

        return rpc

    # -- typed v1 contract (serve.proto) ------------------------------------

    @staticmethod
    def _pb2():
        from . import serve_pb2

        return serve_pb2

    def _typed_parse(self, request: bytes):
        """-> (req, error_response|None). Wire-level garbage and version
        skew surface as TYPED codes, not transport errors."""
        pb = self._pb2()
        try:
            req = pb.PredictRequest.FromString(request)
        except Exception:  # noqa: BLE001 — malformed protobuf
            return None, pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.BAD_REQUEST,
                message="malformed PredictRequest")
        if req.version not in (0, _CONTRACT_VERSION):
            return None, pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.UNSUPPORTED_VERSION,
                message=f"server speaks v{_CONTRACT_VERSION}, "
                        f"got v{req.version}")
        return req, None

    def _typed_body(self, req):
        if req.content_type in ("", "application/json"):
            return json.loads(req.payload) if req.payload else None
        return bytes(req.payload)

    def _typed_result(self, pb, result):
        if isinstance(result, (bytes, bytearray)):
            return pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.OK,
                payload=bytes(result),
                content_type="application/octet-stream")
        return pb.PredictResponse(
            version=_CONTRACT_VERSION, code=pb.OK,
            payload=json.dumps(_jsonable(result)).encode(),
            content_type="application/json")

    def _routes_cached(self):
        """Route set with a short TTL — consulted off the hot path (only
        to classify failures / reject unknown apps) so the controller is
        not a per-request serialization point."""
        import time as _time

        now = _time.monotonic()
        cached = getattr(self, "_routes_cache", None)
        if cached is not None and now - cached[0] < 5.0:
            return cached[1]
        from .controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        routes = set(ray_tpu.get(controller.list_deployments.remote(),
                                 timeout=10))
        self._routes_cache = (now, routes)
        return routes

    def _typed_call(self, req, context, stream: bool):
        """Shared routing for Predict/PredictStream."""
        pb = self._pb2()
        try:
            if req.app not in self._routes_cached():
                return None, pb.PredictResponse(
                    version=_CONTRACT_VERSION, code=pb.APP_NOT_FOUND,
                    message=f"unknown app {req.app!r}; "
                            f"deployed: {sorted(self._routes_cached())}")
        except Exception as e:  # noqa: BLE001
            return None, pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.INTERNAL,
                message=f"controller unavailable: {e}")
        h = self._get_handle(req.app)
        if stream or req.model_id:
            h = h.options(stream=stream,
                          multiplexed_model_id=req.model_id or "")
        try:
            body = self._typed_body(req)
        except Exception as e:  # noqa: BLE001
            return None, pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.BAD_REQUEST,
                message=f"payload does not parse as "
                        f"{req.content_type or 'application/json'}: {e}")
        return (h, body), None

    @staticmethod
    def _deadline(context, default: float = 60.0, cap: float = 600.0
                  ) -> float:
        """Honor the client's gRPC deadline, capped (shared by unary
        paths so the policy can't drift)."""
        remaining = context.time_remaining()
        return min(remaining, cap) if remaining else default

    def _typed_predict(self, request: bytes, context):
        pb = self._pb2()
        req, err = self._typed_parse(request)
        if err is not None:
            return err.SerializeToString()
        routed, err = self._typed_call(req, context, stream=False)
        if err is not None:
            return err.SerializeToString()
        h, body = routed
        try:
            result = ray_tpu.get(h.remote(body),
                                 timeout=self._deadline(context))
        except GetTimeoutError:
            return pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.TIMEOUT,
                message=f"deployment {req.app!r} timed out"
            ).SerializeToString()
        except Exception as e:  # noqa: BLE001
            return pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.INTERNAL,
                message=f"{type(e).__name__}: {e}").SerializeToString()
        return self._typed_result(pb, result).SerializeToString()

    def _typed_predict_stream(self, request: bytes, context):
        pb = self._pb2()
        req, err = self._typed_parse(request)
        if err is not None:
            yield err.SerializeToString()
            return
        routed, err = self._typed_call(req, context, stream=True)
        if err is not None:
            yield err.SerializeToString()
            return
        h, body = routed
        try:
            gen = h.remote(body)
            while True:
                try:
                    item = gen.next(timeout=600.0)
                except StopIteration:
                    break
                yield self._typed_result(pb, item).SerializeToString()
        except GetTimeoutError:
            yield pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.TIMEOUT,
                message="stream item timed out").SerializeToString()
        except Exception as e:  # noqa: BLE001
            yield pb.PredictResponse(
                version=_CONTRACT_VERSION, code=pb.INTERNAL,
                message=f"{type(e).__name__}: {e}").SerializeToString()

    def _routes_rpc(self, request: bytes, context) -> bytes:
        import grpc

        try:
            from .controller import CONTROLLER_NAME

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            routes = ray_tpu.get(controller.list_deployments.remote(),
                                 timeout=10)
            return json.dumps({"deployments": routes}).encode()
        except Exception as e:  # noqa: BLE001 — same mapping as the
            # unary/stream handlers: INTERNAL + "TypeName: msg"
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")

    # -- actor surface -------------------------------------------------------

    def address(self) -> tuple:
        return (self._host, self._port)

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> bool:
        # stop() returns an event; wait it out so in-flight RPCs drain
        # before the caller kills this actor
        self._server.stop(grace=1.0).wait()
        return True


from .http_asyncio import _jsonable  # noqa: E402 — single shared coercion


def grpc_call(address: tuple, deployment: str, payload=None,
              model_id: str = "", timeout: float = 60.0):
    """Client helper (also shows the wire contract for non-Python
    clients): unary JSON call to a deployment."""
    import grpc

    with grpc.insecure_channel(f"{address[0]}:{address[1]}") as chan:
        fn = chan.unary_unary(
            f"{_PREFIX}{deployment}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        md = (("model_id", model_id),) if model_id else None
        out = fn(json.dumps(payload).encode(), metadata=md,
                 timeout=timeout)
        return json.loads(out)


def grpc_stream(address: tuple, deployment: str, payload=None,
                timeout: float = 60.0):
    """Client helper: streaming call yielding parsed JSON messages."""
    import grpc

    with grpc.insecure_channel(f"{address[0]}:{address[1]}") as chan:
        fn = chan.unary_stream(
            f"{_PREFIX}{deployment}/stream",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        for msg in fn(json.dumps(payload).encode(), timeout=timeout):
            yield json.loads(msg)
