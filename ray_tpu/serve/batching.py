"""@serve.batch — dynamic request batching.

Equivalent of the reference's serve.batching (ref:
python/ray/serve/batching.py _BatchQueue: requests accumulate until
max_batch_size or batch_wait_timeout_s, then one call runs the whole
batch). On TPU this is the single most valuable Serve feature: a
pjit-compiled model step costs the same for 1 or 32 rows, so batching
multiplies throughput by the batch size.

The reference's implementation is asyncio-native; replicas here execute
requests on an actor thread pool (max_concurrency > 1), so this is the
threaded equivalent: callers block on a per-item Future while a flusher
thread drains the queue. Batching therefore requires
max_concurrent_queries > 1 on the deployment — same constraint as the
reference (no concurrency, nothing to batch).

    @serve.deployment(max_concurrent_queries=64)
    class Model:
        @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.01)
        def __call__(self, inputs: list) -> list:
            return model_step(np.stack(inputs)).tolist()
"""
from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max(1, int(max_batch_size))
        self._timeout = float(batch_wait_timeout_s)
        self._lock = threading.Lock()
        self._items: List[tuple] = []  # (arg, Future)
        self._wake = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None

    def submit(self, arg: Any) -> Future:
        fut: Future = Future()
        with self._lock:
            # lazy flusher start: a queue that loses a creation race is
            # never submitted to and must not leak a parked thread
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._flusher, daemon=True, name="serve-batch")
                self._thread.start()
            self._items.append((arg, fut))
            self._wake.notify()
        return fut

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def _flusher(self) -> None:
        while True:
            with self._lock:
                while not self._items:
                    self._wake.wait()
                # first item arrived: linger up to the timeout for more
                deadline = time.monotonic() + self._timeout
                while (len(self._items) < self._max
                       and time.monotonic() < deadline):
                    self._wake.wait(timeout=max(
                        0.0, deadline - time.monotonic()))
                batch, self._items = (self._items[:self._max],
                                      self._items[self._max:])
            args = [a for a, _ in batch]
            futs = [f for _, f in batch]
            try:
                results = self._fn(args)
                if results is None or len(results) != len(args):
                    raise ValueError(
                        f"@serve.batch function returned "
                        f"{0 if results is None else len(results)} results "
                        f"for a batch of {len(args)}")
            except BaseException as e:  # noqa: BLE001 — ship to every caller
                for f in futs:
                    f.set_exception(e)
                continue
            for f, r in zip(futs, results):
                f.set_result(r)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped function receives a LIST of the individual
    call arguments and must return a same-length list of results. Each
    caller passes one item and gets its own result back.
    (ref: python/ray/serve/batching.py serve.batch)

    NOTE: this closure must stay free of locks/threads — deployment
    classes travel through cloudpickle, which serializes these inner
    functions by value. Queue state is created lazily AFTER unpickling
    and attached to the replica instance; the GIL-atomic
    __dict__.setdefault resolves creation races."""

    def deco(fn: Callable) -> Callable:
        qattr = f"__rtpu_batch_queue_{fn.__name__}"

        def queue_for(instance, wrapper) -> _BatchQueue:
            holder = instance if instance is not None else wrapper
            q = holder.__dict__.get(qattr)
            if q is None:
                target = (functools.partial(fn, instance)
                          if instance is not None else fn)
                q = holder.__dict__.setdefault(
                    qattr, _BatchQueue(target, max_batch_size,
                                       batch_wait_timeout_s))
            return q

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:        # bound method: (self, item)
                instance, item = args
            elif len(args) == 1:      # free function: (item,)
                instance, item = None, args[0]
            else:
                raise TypeError(
                    "@serve.batch functions take exactly one request "
                    "argument (plus self for methods)")
            return queue_for(instance, wrapper).submit(item).result()

        wrapper._rtpu_serve_batch = True  # noqa: SLF001 — introspection tag
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
