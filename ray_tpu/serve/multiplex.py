"""Model multiplexing — many models per replica with LRU residency.

Equivalent of the reference's serve.multiplexed (ref:
python/ray/serve/multiplex.py _ModelMultiplexWrapper;
api.py:multiplexed). A deployment decorates its loader with
@serve.multiplexed(max_num_models_per_replica=N); requests carry a
model id via handle.options(multiplexed_model_id=...), the router
prefers replicas that already host that model (routing map from
replica-reported ids), and the replica's wrapper loads/evicts models
LRU. On TPU this is the many-LoRA/many-finetune serving pattern: N
adapter sets resident per mesh replica, routed by id.
"""
from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from typing import Any, Callable, List

# set by the replica around each request (ref: serve/context.py
# _serve_request_context.multiplexed_model_id)
_current_model_id: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")

MUX_KWARG = "__multiplexed_model_id__"  # internal request annotation


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller asked for (ref:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


class _BoundMultiplex:
    """Per-replica-instance LRU of loaded models."""

    def __init__(self, obj: Any, fn: Callable, max_models: int):
        self._obj = obj
        self._fn = fn
        self._max = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()

    def __call__(self, model_id: str) -> Any:
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        # load OUTSIDE the lock: loads can be slow (checkpoint reads) and
        # other requests may be serving resident models meanwhile
        model = self._fn(self._obj, model_id)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                _, evicted = self._models.popitem(last=False)
                # release via a conventional hook, never __del__ directly —
                # the interpreter calls __del__ again at GC, and models
                # freeing device memory/files there would double-release
                for hook in ("unload", "close"):
                    fn = getattr(evicted, hook, None)
                    if callable(fn):
                        try:
                            fn()
                        except Exception:
                            pass
                        break
        return model

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models)


class _MultiplexedMethod:
    """Descriptor form of the decorator: binds one LRU wrapper per
    replica instance and registers it so the replica can report its
    resident model ids to the router."""

    REGISTRY_ATTR = "__serve_multiplex_wrappers__"

    def __init__(self, fn: Callable, max_models: int):
        self._fn = fn
        self._max = max_models
        self._attr = f"__serve_mux_{fn.__name__}__"

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        bound = obj.__dict__.get(self._attr)
        if bound is None:
            bound = _BoundMultiplex(obj, self._fn, self._max)
            obj.__dict__[self._attr] = bound
            registry = obj.__dict__.setdefault(self.REGISTRY_ATTR, [])
            registry.append(bound)
        return bound


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for a deployment's model-loader method:

        @serve.multiplexed(max_num_models_per_replica=4)
        def get_model(self, model_id: str): ...

        def __call__(self, req):
            model = self.get_model(serve.get_multiplexed_model_id())
    """
    def deco(fn: Callable) -> _MultiplexedMethod:
        return _MultiplexedMethod(fn, max_num_models_per_replica)

    return deco


def resident_model_ids(callable_obj: Any) -> List[str]:
    """All model ids currently loaded across a replica's multiplex
    wrappers (reported to the router for locality-aware picks)."""
    out: List[str] = []
    for w in getattr(callable_obj, _MultiplexedMethod.REGISTRY_ATTR, []):
        out.extend(w.model_ids())
    return out
