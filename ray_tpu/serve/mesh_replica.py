"""MeshDeployment — a replica whose compute is a gang of mesh workers.

The TPU-native twist on Serve (SURVEY.md §7: "a replica spans multiple
hosts, unlike Ray, so the router must target mesh groups"): one replica =
one MeshGroup of host actors that each own a slice of the device mesh and
enter the same pjit-compiled program. The router still sees a single
replica actor (this class); fan-out to the gang happens inside
handle_request via MeshGroup.run, so power-of-two-choices and
max_concurrent_queries compose unchanged.

Subclass and implement:
    build(config)          -> (params, apply_fn) built ONCE per worker
    preprocess(request)    -> batch (host side, optional)
    postprocess(outputs)   -> response (optional)
"""
from __future__ import annotations

from typing import Any, Optional

from ..parallel import MeshGroup, MeshSpec
from ..parallel.mesh_group import MeshWorkerMixin


class _MeshInferenceWorker(MeshWorkerMixin):
    """One host of the replica's gang: builds the model and jits the
    sharded forward on its mesh slice. ``self.mesh_owner`` (the shared
    ownership layer from parallel.sharding) is available to build fns
    that want SpecLayout-driven shardings rather than raw mesh axes."""

    def build_model(self, build_blob: bytes, config: Optional[dict]) -> bool:
        import cloudpickle

        build = cloudpickle.loads(build_blob)
        self._params, self._apply = build(self.mesh, config or {})
        return True

    def infer(self, batch):
        return self._apply(self._params, batch)


class MeshDeployment:
    """User-facing base: a deployment class hosting a sharded model.

    build_fn(mesh, config) -> (params, apply_fn) runs on every gang
    worker; apply_fn(params, batch) is the pjit-compiled forward.
    """

    def __init__(self, build_fn, *, num_workers: int = 1,
                 spec: Optional[MeshSpec] = None,
                 devices_per_worker: Optional[int] = None,
                 coordinator: Optional[str] = None,
                 config: Optional[dict] = None):
        import cloudpickle

        self._group = MeshGroup(num_workers=num_workers, spec=spec,
                                worker_cls=_MeshInferenceWorker,
                                devices_per_process=devices_per_worker,
                                coordinator=coordinator)
        blob = cloudpickle.dumps(build_fn)
        self._group.run(lambda w: w.build_model(blob, config))
        self._config = config

    def __call__(self, request: Any):
        batch = self.preprocess(request)
        # SPMD gang entry: every worker runs the same program on its mesh
        # slice; worker 0's (fully-addressable on single-host meshes)
        # output is the reply
        outs = self._group.run(lambda w, b=batch: w.infer(b))
        return self.postprocess(outs[0])

    def preprocess(self, request: Any):
        return request

    def postprocess(self, output: Any):
        return output

    def check_health(self) -> None:
        # a dead gang worker fails the next ping -> replica replaced
        import ray_tpu

        ray_tpu.get([w.mesh_run.remote(_noop_blob())
                     for w in self._group.workers], timeout=30)

    def __del__(self):
        try:
            self._group.shutdown()
        except Exception:
            pass


def _noop_blob() -> bytes:
    import cloudpickle

    return cloudpickle.dumps(lambda w: True)
