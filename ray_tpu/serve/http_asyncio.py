"""Asyncio HTTP ingress — the production proxy.

Equivalent of the reference's uvicorn/ASGI HTTPProxyActor (ref:
python/ray/serve/_private/http_proxy.py:873). No ASGI framework ships in
this image, so this is a native asyncio HTTP/1.1 server: one event loop
owns all connections (keep-alive, pipel­ined clients, slow readers cost a
task each, not a thread each), and deployment calls run on a bounded
thread pool so a slow replica can never stall the accept/IO path. The
stdlib-http.server proxy (http_proxy.py) remains as the zero-dependency
fallback; serve.start_http_proxy picks this one by default.

Routes (same surface as http_proxy.py):
    POST /<deployment>            body = JSON  -> result as JSON
    GET  /<deployment>?q=...      query dict -> result as JSON
    ...?stream=1                  chunked NDJSON streaming response
    ...?model_id=<id>             multiplexed model routing
    GET  /-/routes                deployment listing
    GET  /-/healthz               proxy liveness
"""
from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.util import tracing

_MAX_BODY = 64 << 20  # 64 MiB request cap
_MAX_HEADER = 64 << 10


class AsyncHTTPProxy:
    """Actor hosting the asyncio server; the loop runs on its own thread
    (actor method calls return immediately)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 num_handler_threads: int = 64):
        self._host = host
        self._handles: Dict[str, object] = {}
        self._pool = ThreadPoolExecutor(num_handler_threads,
                                        thread_name_prefix="serve-call")
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._server: Optional[asyncio.AbstractServer] = None
        self._port = 0
        self._requests = 0
        self._errors = 0

        def runner():
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(self._start(host, port))
            self._started.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="serve-asyncio")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("asyncio proxy failed to start")

    async def _start(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(self._serve_conn, host,
                                                  port)
        self._port = self._server.sockets[0].getsockname()[1]

    # -- connection handling -----------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (ValueError, UnicodeDecodeError,
                        asyncio.LimitOverrunError):
                    # malformed request (bad Content-Length, non-UTF8
                    # headers, oversized request line): answer 400, don't
                    # leak an unhandled-task exception per port-scan probe
                    self._write_json(writer, 400,
                                     {"error": "malformed request"}, False)
                    await writer.drain()
                    break
                if req is None:
                    break
                keep = await self._handle_request(writer, *req)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, target, _version = line.decode().split(None, 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        total = 0
        while True:
            h = await reader.readline()
            total += len(h)
            if total > _MAX_HEADER:
                return None
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length") or 0)
        if n < 0 or n > _MAX_BODY:
            raise ValueError(f"bad content-length {n}")
        body = await reader.readexactly(n) if n else b""
        return method, target, headers, body

    async def _handle_request(self, writer, method, target, headers,
                              body) -> bool:
        self._requests += 1
        keep = headers.get("connection", "keep-alive").lower() != "close"
        url = urlparse(target)
        name = url.path.strip("/")
        q = parse_qs(url.query)
        if name == "-/healthz":
            self._write_json(writer, 200, {"status": "ok"}, keep)
            return keep
        if name == "-/routes":
            try:
                routes = await self._in_pool(self._routes)
                self._write_json(writer, 200, routes, keep)
            except Exception as e:  # noqa: BLE001
                self._write_json(writer, 500, {"error": str(e)}, keep)
            return keep
        if not name:
            self._write_json(writer, 404, {"error": "no deployment in path"},
                             keep)
            return keep
        if method == "POST":
            try:
                data = json.loads(body) if body else None
            except json.JSONDecodeError:
                self._write_json(writer, 400, {"error": "body must be JSON"},
                                 keep)
                return keep
        else:
            from .handle import PROXY_CONTROL_PARAMS

            data = {k: v[0] if len(v) == 1 else v for k, v in q.items()
                    if k not in PROXY_CONTROL_PARAMS} or None
        mux = (q.get("model_id") or [""])[0]
        # session-aware routing: shared precedence rule (?session= beats
        # payload "session_id") so both proxies pin identically
        from .handle import extract_session

        sess = extract_session(q, data)
        stream_mode = (q.get("stream") or ["0"])[0]
        # trace ingress: continue the client's W3C traceparent or open
        # a fresh root. Context travels as DATA from here (the handle
        # call runs on the pool, where a loop-thread contextvar would
        # not follow); the root span records once the reply is done.
        parent = tracing.parse_traceparent(headers.get("traceparent"))
        trace_id = parent[0] if parent else tracing.new_trace_id()
        trace_ctx = (trace_id, tracing.new_span_id())
        root_parent = (trace_id, parent[1] if parent else None)
        t0 = time.time()
        if stream_mode in ("1", "true", "sse"):
            try:
                ok = await self._stream_response(writer, name, data, mux,
                                                 sess,
                                                 sse=stream_mode == "sse",
                                                 trace_ctx=trace_ctx)
            except Exception as e:  # noqa: BLE001 — pre-header failure
                # nothing on the wire yet (submission/iterator setup
                # failed): a normal 500 is still possible
                self._errors += 1
                self._write_json(writer, 500,
                                 {"error": f"{type(e).__name__}: {e}"},
                                 keep)
                self._end_span(root_parent, trace_ctx, t0, name, sess,
                               True, 500, f"{type(e).__name__}: {e}")
                return keep
            self._end_span(root_parent, trace_ctx, t0, name, sess, True,
                           200 if ok else 0, "" if ok else "stream_failed")
            if not ok:
                # mid-stream failure: headers were already sent and the
                # connection was closed — a late 500 would corrupt the
                # chunk framing
                self._errors += 1
                return False
            return keep
        try:
            result = await self._in_pool(self._call_blocking, name, data,
                                         mux, sess, trace_ctx)
            self._write_json(writer, 200, _jsonable(result), keep,
                             trace_ctx)
            self._end_span(root_parent, trace_ctx, t0, name, sess, False,
                           200, "")
        except Exception as e:  # noqa: BLE001
            self._errors += 1
            self._write_json(writer, 500,
                             {"error": f"{type(e).__name__}: {e}"}, keep,
                             trace_ctx)
            self._end_span(root_parent, trace_ctx, t0, name, sess, False,
                           500, f"{type(e).__name__}: {e}")
        return keep

    @staticmethod
    def _end_span(root_parent, trace_ctx, t0, name, sess, stream,
                  status, err) -> None:
        tracing.record_span(
            "http.request", root_parent, t0, span_id=trace_ctx[1],
            ingress=True, deployment=name, session=sess, stream=stream,
            status=status, error=err)

    async def _stream_response(self, writer, name, data, mux,
                               sess: str = "", sse: bool = False,
                               trace_ctx=None) -> bool:
        """Chunked streaming: generator items are pulled on the pool
        (each next() blocks on the replica) and written as they arrive —
        NDJSON lines by default, SSE `data:` frames with a terminal
        `event: done` under ?stream=sse. Exceptions BEFORE the headers
        go out propagate (caller sends a 500); a mid-stream failure
        closes the connection and returns False."""
        # activate around the synchronous submission only (no await in
        # between, so no other handler can observe the contextvar): the
        # handle captures the context into the stream generator, where
        # it travels as data across pool-thread pulls
        token = tracing.activate(trace_ctx)
        try:
            gen = self._get_handle(name).options(
                stream=True, multiplexed_model_id=mux,
                session_id=sess).remote(data)
        finally:
            tracing.deactivate(token)
        ctype = b"text/event-stream" if sse else b"application/x-ndjson"
        tp = (b"traceparent: "
              + tracing.format_traceparent(trace_ctx).encode()
              + b"\r\n") if trace_ctx else b""
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: " + ctype + b"\r\n" + tp
                     + b"Transfer-Encoding: chunked\r\n\r\n")
        _SENTINEL = object()

        def pull():
            # the timeout lives INSIDE the blocking call: a hung replica
            # releases the pool thread after 600s (GetTimeoutError) —
            # an outer asyncio.wait_for would free only the coroutine
            # while the thread stayed pinned in next() forever
            try:
                return gen.next(timeout=600.0)
            except StopIteration:
                return _SENTINEL
        def frame(item) -> bytes:
            body = json.dumps(_jsonable(item)).encode()
            if sse:
                return b"data: " + body + b"\n\n"
            return body + b"\n"

        try:
            while True:
                item = await self._in_pool(pull)
                if item is _SENTINEL:
                    break
                payload = frame(item)
                writer.write(f"{len(payload):X}\r\n".encode())
                writer.write(payload + b"\r\n")
                await writer.drain()
        except Exception:  # noqa: BLE001
            # headers are on the wire: drop the connection so the client
            # sees a framing error, not a truncated-but-"complete" stream
            writer.close()
            return False
        if sse:
            # terminal frame: SSE clients can't tell finished from dropped
            done = b"event: done\ndata: [DONE]\n\n"
            writer.write(f"{len(done):X}\r\n".encode())
            writer.write(done + b"\r\n")
        writer.write(b"0\r\n\r\n")
        return True

    # -- helpers ------------------------------------------------------------

    def _in_pool(self, fn, *args):
        return self._loop.run_in_executor(self._pool, fn, *args)

    def _call_blocking(self, name: str, data, mux: str, sess: str = "",
                       trace_ctx=None):
        h = self._get_handle(name)
        if mux or sess:
            h = h.options(multiplexed_model_id=mux, session_id=sess)
        token = tracing.activate(trace_ctx)
        try:
            return ray_tpu.get(h.remote(data), timeout=60)
        finally:
            tracing.deactivate(token)

    def _get_handle(self, name: str):
        from .handle import DeploymentHandle

        h = self._handles.get(name)
        if h is None:
            h = self._handles[name] = DeploymentHandle(name)
        return h

    def _routes(self) -> dict:
        from .controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return {"deployments":
                ray_tpu.get(controller.list_deployments.remote(),
                            timeout=10)}

    @staticmethod
    def _write_json(writer, code: int, payload, keep: bool,
                    trace_ctx=None) -> None:
        body = json.dumps(payload).encode()
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  500: "Internal Server Error"}.get(code, "")
        conn = "keep-alive" if keep else "close"
        tp = ("traceparent: "
              + tracing.format_traceparent(trace_ctx) + "\r\n") \
            if trace_ctx else ""
        writer.write((f"HTTP/1.1 {code} {reason}\r\n"
                      f"Content-Type: application/json\r\n"
                      f"Content-Length: {len(body)}\r\n" + tp +
                      f"Connection: {conn}\r\n\r\n").encode())
        writer.write(body)

    # -- actor surface -------------------------------------------------------

    def address(self) -> tuple:
        return (self._host, self._port)

    def stats(self) -> dict:
        return {"requests": self._requests, "errors": self._errors}

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> bool:
        def _close():
            # close the listening socket first: a stopped loop with an
            # open server would keep accepting connections that nothing
            # ever services (clients hang instead of connection-refused)
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_close)
        self._pool.shutdown(wait=False)
        return True


def _jsonable(value):
    import numpy as np

    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.integer, np.floating)):
        return value.item()
    return value
