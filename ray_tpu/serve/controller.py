"""ServeController — the reconcile loop.

Equivalent of the reference's controller actor (ref:
python/ray/serve/_private/controller.py:74; run_control_loop :298) with
DeploymentState semantics (ref: deployment_state.py — target vs running
replicas, health checks, rolling updates, scale up/down) collapsed into
one actor. Replicas are actors the controller owns; handles discover them
via get_replicas (the long-poll analog is version-stamped polling,
ref: long_poll.py:187).
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

from .config import HEALTHY, UNHEALTHY, UPDATING, DeploymentConfig

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _ReplicaState:
    def __init__(self, handle, version: int, tag: str):
        self.handle = handle
        self.version = version
        self.tag = tag
        self.starting = True           # until first successful ping
        self.started_at = time.monotonic()
        self.last_ongoing = 0
        # preemption-notice draining (docs/FAULT_TOLERANCE.md
        # "Elasticity"): a draining replica takes no NEW requests
        # (excluded from get_replicas), finishes what it has, and is
        # killed once idle or at the drain deadline — whichever first
        self.draining = False
        self.drain_deadline = 0.0
        self.drain_marked_at = 0.0
        # prefix-cache warmth from the health ping (replica.py): the
        # session router's tie-break and the scale-down victim pick
        # both prefer keeping warm replicas
        self.cache_hit_rate = 0.0
        self.prefix_blocks_resident = 0


class _DeploymentState:
    def __init__(self, name: str, blob: bytes, init_args, init_kwargs,
                 config: DeploymentConfig):
        self.name = name
        self.blob = blob
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.version = 0
        self.replicas: List[_ReplicaState] = []
        self.status = UPDATING
        self.target = (config.autoscaling.min_replicas
                       if config.autoscaling else config.num_replicas)
        self._last_scale = 0.0
        self.deleted = False


class ServeController:
    def __init__(self, control_period_s: float = 0.5):
        self._period = control_period_s
        self._deployments: Dict[str, _DeploymentState] = {}
        # deleted-then-redeployed states drain here until their replicas die
        self._graveyard: list = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._control_loop,
                                        daemon=True, name="serve-reconcile")
        self._thread.start()

    # -- API ------------------------------------------------------------------

    def deploy(self, name: str, blob: bytes, init_args, init_kwargs,
               config: DeploymentConfig) -> bool:
        with self._lock:
            st = self._deployments.get(name)
            if st is not None and st.deleted:
                self._graveyard.append(st)  # loop still owns its replicas
                st = None
            if st is None:
                st = _DeploymentState(name, blob, init_args, init_kwargs,
                                      config)
                self._deployments[name] = st
                return True
            code_changed = (blob != st.blob
                            or init_args != st.init_args
                            or init_kwargs != st.init_kwargs
                            or config.version_fields()
                            != st.config.version_fields())
            st.blob, st.init_args, st.init_kwargs = blob, init_args, init_kwargs
            st.config = config
            if not config.autoscaling:
                st.target = config.num_replicas
            if code_changed:
                st.version += 1         # triggers rolling replacement
                st.status = UPDATING
            return True

    def delete(self, name: str) -> bool:
        # mark-and-reconcile rather than pop: an in-flight _reconcile
        # holding this state must not restart replicas for a deployment
        # that no longer exists — the loop drains it and removes the entry
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return False
            st.deleted = True
            st.target = 0
        return True

    @staticmethod
    def _routable(st: _DeploymentState):
        """Replicas the router may assign work to — the ONE routability
        definition get_replicas and replica_warmth both use."""
        return [r for r in st.replicas
                if not r.starting and not r.draining
                and r.version == st.version]

    @staticmethod
    def _warmth_of(replicas) -> Dict[str, float]:
        return {r.handle._actor_id.hex(): float(r.prefix_blocks_resident)
                for r in replicas}

    def get_replicas(self, name: str, with_warmth: bool = False):
        """-> (version, max_concurrent_queries, [actor handles]) for
        routing — plus the cache-warmth map (actor hex -> resident
        prefix blocks) when ``with_warmth``, so the handle gets both in
        ONE round trip per refresh. Draining replicas are EXCLUDED: the
        router stops assigning new requests/streams the moment its next
        refresh lands, while in-flight work on them runs to
        completion."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return (0, 0, [], {}) if with_warmth else (0, 0, [])
            routable = self._routable(st)
            handles = [r.handle for r in routable]
            if not with_warmth:
                return (st.version, st.config.max_concurrent_queries,
                        handles)
            return (st.version, st.config.max_concurrent_queries,
                    handles, self._warmth_of(routable))

    def drain_replicas(self, actor_id_hexes, grace_s: float = 30.0) -> int:
        """Preemption-notice draining: mark every replica whose actor id
        is in ``actor_id_hexes`` (hex strings) as draining, across all
        deployments. The runtime calls this when a node gets a
        ``NODE_PREEMPTING`` event; operators/tests may call it directly
        for scripted scale-downs. Returns the number of replicas newly
        marked. Replacement replicas start on the next reconcile pass
        (draining replicas stop counting toward target), and the
        drained corpse is killed once idle or at the deadline."""
        wanted = {h.lower() for h in actor_id_hexes}
        marked = []
        deadline = time.monotonic() + max(0.0, float(grace_s))
        with self._lock:
            for st in self._deployments.values():
                for r in st.replicas:
                    if r.draining:
                        continue
                    if r.handle._actor_id.hex().lower() in wanted:
                        r.draining = True
                        r.drain_deadline = deadline
                        r.drain_marked_at = time.monotonic()
                        marked.append(r)
        for r in marked:
            # the replica reports draining in its own health ping from
            # here on (observability surface; the routing decision
            # already happened via get_replicas exclusion)
            try:
                r.handle.set_draining.options(
                    concurrency_group="control").remote(True)
            except Exception:
                pass
        return len(marked)

    def get_slo(self, name: str) -> Optional[float]:
        """The deployment's latency SLO target in seconds (None = no
        SLO). Handles fetch this once per version change and count every
        routed request into ray_tpu_serve_slo_{ok,violated}_total."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return None
            return getattr(st.config, "slo_target_s", None)

    def replica_warmth(self, name: str) -> Dict[str, float]:
        """actor_id hex -> CURRENT resident prefix-block count for
        every routable replica (the health-ping `cache_stats` surface).
        Resident blocks, not the cumulative hit rate, is the warmth
        signal: a cleared or freshly-restarted cache reads 0 here no
        matter what its historical ratio was. Introspection twin of the
        map `get_replicas(..., with_warmth=True)` piggybacks to the
        router."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return {}
            return self._warmth_of(self._routable(st))

    def status(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {"status": st.status, "version": st.version,
                       "target": st.target,
                       "running": sum(1 for r in st.replicas
                                      if not r.starting and not r.draining),
                       "draining": sum(1 for r in st.replicas
                                       if r.draining),
                       "cache_blocks_resident": sum(
                           r.prefix_blocks_resident for r in st.replicas)}
                for name, st in self._deployments.items() if not st.deleted
            }

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> bool:
        self._stop.set()
        with self._lock:
            states = list(self._deployments.values())
            self._deployments.clear()
        for st in states:
            for r in st.replicas:
                self._kill(r)
        return True

    # -- reconciliation -------------------------------------------------------

    def _control_loop(self) -> None:
        while not self._stop.is_set():
            try:
                with self._lock:
                    states = (list(self._deployments.values())
                              + list(self._graveyard))
                for st in states:
                    self._reconcile(st)
            except Exception:
                import traceback

                traceback.print_exc()
            self._stop.wait(self._period)

    def _reconcile(self, st: _DeploymentState) -> None:
        if st.deleted:
            with self._lock:
                victims = list(st.replicas)
                st.replicas.clear()
            for r in victims:
                self._kill(r, st.config.graceful_shutdown_timeout_s)
            with self._lock:
                if self._deployments.get(st.name) is st:
                    del self._deployments[st.name]
                if st in self._graveyard:
                    self._graveyard.remove(st)
            return
        self._health_check(st)
        self._autoscale(st)
        with self._lock:
            current = list(st.replicas)
            target = st.target
            version = st.version
        # drain completion: a draining replica dies the moment it is
        # idle (after at least one post-mark health ping, so a stream
        # assigned just before the mark is visible) or at the deadline.
        # It stopped counting toward target below, so its replacement
        # is already starting — notice → drain → handoff → clean exit.
        now = time.monotonic()
        for r in [r for r in current if r.draining]:
            settled = now - getattr(r, "drain_marked_at", 0.0) \
                > st.config.health_check_period_s
            idle = not r.starting and r.last_ongoing == 0 and settled
            if idle or now > r.drain_deadline:
                with self._lock:
                    if r in st.replicas:
                        st.replicas.remove(r)
                self._kill(r, st.config.graceful_shutdown_timeout_s)
                current.remove(r)
        active = [r for r in current if not r.draining]
        running = [r for r in active if not r.starting]
        # rolling update: at most one old replica replaced per cycle, and
        # only while the deployment is at healthy strength (ref:
        # deployment_state.py rolling update semantics)
        old = [r for r in running if r.version != version]
        if old and len(running) >= target:
            victim = old[0]
            with self._lock:
                if victim in st.replicas:
                    st.replicas.remove(victim)
            self._kill(victim, st.config.graceful_shutdown_timeout_s)
            active = [r for r in active if r is not victim]
        # scale up (draining replicas do not count: their capacity is
        # already promised away, so replacements start NOW)
        while len(active) < target:
            r = self._start_replica(st, version)
            if r is None:
                break
            active.append(r)
        # scale down (starting first; among running, the CACHE-COLDEST
        # goes first — killing a warm replica throws away resident
        # prefix KV that sessions pinned to it still want — then
        # newest). Warmth = CURRENT resident blocks, not the cumulative
        # hit rate: a cleared cache is cold regardless of its history
        while len(active) > target:
            victim = sorted(active,
                            key=lambda r: (not r.starting,
                                           r.prefix_blocks_resident,
                                           -r.started_at))[0]
            with self._lock:
                if victim in st.replicas:
                    st.replicas.remove(victim)
            self._kill(victim, st.config.graceful_shutdown_timeout_s)
            active.remove(victim)
        with self._lock:
            healthy = sum(1 for r in st.replicas
                          if not r.starting and not r.draining
                          and r.version == version)
            if healthy >= st.target and not old:
                st.status = HEALTHY
            elif not st.replicas:
                st.status = UNHEALTHY
            else:
                st.status = UPDATING

    def _health_check(self, st: _DeploymentState) -> None:
        with self._lock:
            replicas = list(st.replicas)
        if not replicas:
            return
        probes = [(r, r.handle.ping.options(
            concurrency_group="control").remote()) for r in replicas]
        deadline = time.monotonic() + st.config.health_check_timeout_s
        for r, ref in probes:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                info = ray_tpu.get(ref, timeout=timeout)
                r.starting = False
                # autoscaling load = max(in-flight RPCs, app-reported
                # backlog): streaming/engine replicas report queue_depth
                # in the ping (replica.py) — in-flight alone undercounts
                # a deep engine queue behind one streaming call
                r.last_ongoing = max(int(info.get("ongoing", 0)),
                                     int(info.get("queue_depth", 0)))
                r.cache_hit_rate = float(info.get("cache_hit_rate", 0.0))
                r.prefix_blocks_resident = int(
                    info.get("prefix_blocks_resident", 0))
            except Exception:
                grace = st.config.health_check_timeout_s * 3
                if r.starting and time.monotonic() - r.started_at < grace:
                    continue  # still constructing
                with self._lock:
                    if r in st.replicas:
                        st.replicas.remove(r)
                self._kill(r, st.config.graceful_shutdown_timeout_s)

    def _autoscale(self, st: _DeploymentState) -> None:
        cfg = st.config.autoscaling
        if cfg is None:
            return
        with self._lock:
            running = [r for r in st.replicas
                       if not r.starting and not r.draining]
            ongoing = sum(r.last_ongoing for r in running)
        if not running:
            return
        import math

        desired = max(cfg.min_replicas,
                      min(cfg.max_replicas,
                          math.ceil(ongoing / cfg.target_ongoing_requests)))
        now = time.monotonic()
        if desired > st.target and now - st._last_scale >= cfg.upscale_delay_s:
            st.target = desired
            st._last_scale = now
        elif (desired < st.target
              and now - st._last_scale >= cfg.downscale_delay_s):
            st.target = desired
            st._last_scale = now

    # -- replica ops ----------------------------------------------------------

    def _start_replica(self, st: _DeploymentState,
                       version: int) -> Optional[_ReplicaState]:
        from .replica import Replica

        tag = f"{st.name}#{uuid.uuid4().hex[:6]}"
        opts = dict(st.config.ray_actor_options)
        opts.setdefault("num_cpus", 1.0)
        # real request parallelism must match the router's admission cap —
        # and batching only happens when requests overlap. The "control"
        # lane keeps health pings and queue-depth probes off the request
        # threads, so a saturated replica still answers its router
        # (ref: replica.py max_concurrent_queries + concurrency groups)
        opts.setdefault("max_concurrency",
                        int(st.config.max_concurrent_queries))
        # MERGE (not setdefault): user-supplied groups must not evict the
        # control lane, or every health ping / depth probe errors out
        cg = dict(opts.get("concurrency_groups") or {})
        cg.setdefault("control", 2)
        opts["concurrency_groups"] = cg
        try:
            cls = ray_tpu.remote(Replica)
            handle = cls.options(**opts).remote(
                st.blob, st.init_args, st.init_kwargs,
                st.config.user_config, st.name, tag, version)
        except Exception:
            import traceback

            traceback.print_exc()
            return None
        r = _ReplicaState(handle, version, tag)
        with self._lock:
            st.replicas.append(r)
        return r

    def _kill(self, r: _ReplicaState, grace_s: float = 5.0) -> None:
        try:
            ray_tpu.get(r.handle.shutdown.remote(), timeout=grace_s)
        except Exception:
            pass
        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass


def get_or_create_controller():
    """The controller is a named detached actor shared by all drivers in
    the session (ref: serve/_private/client.py get_controller)."""
    cls = ray_tpu.remote(ServeController)
    return cls.options(name=CONTROLLER_NAME, lifetime="detached",
                       get_if_exists=True, max_restarts=1).remote()
