"""Deployment configuration (ref: python/ray/serve/config.py
DeploymentConfig/AutoscalingConfig)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Request-based autoscaling (ref: serve/_private/autoscaling_policy.py:12
    — desired = ongoing_requests / target, clamped and smoothed)."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 5.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    user_config: Optional[Any] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling: Optional[AutoscalingConfig] = None
    # end-to-end latency SLO for this deployment (seconds, None = no
    # SLO): every routed request lands in
    # ray_tpu_serve_slo_{ok,violated}_total{deployment=...} depending on
    # whether it finished inside the target
    slo_target_s: Optional[float] = None

    def version_fields(self) -> tuple:
        """Changes to these require replacing replicas (rolling update);
        num_replicas alone only rescales (ref: deployment_state.py
        lightweight-update split)."""
        return (repr(self.user_config), repr(self.ray_actor_options))


# deployment statuses (ref: serve/schema.py DeploymentStatus)
UPDATING = "UPDATING"
HEALTHY = "HEALTHY"
UNHEALTHY = "UNHEALTHY"
