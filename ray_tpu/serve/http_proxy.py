"""HTTP ingress.

Equivalent of the reference's HTTPProxyActor (ref:
python/ray/serve/_private/http_proxy.py:873 — uvicorn/ASGI). Here a
stdlib ThreadingHTTPServer inside an actor: no external web framework in
the image, and the proxy is off the TPU hot path by design. Requests:

    POST /<deployment>       body = JSON  -> result as JSON
    GET  /<deployment>?q=... -> calls with the query dict
    GET  /-/routes           -> deployment listing
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.util import tracing


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from .handle import DeploymentHandle

        self._handles: Dict[str, DeploymentHandle] = {}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            # chunked transfer-encoding is an HTTP/1.1 construct; the
            # stdlib default of HTTP/1.0 would make streamed replies
            # invalid for spec-compliant clients
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                ctx = getattr(self, "_trace_ctx", None)
                if ctx is not None:
                    # egress: clients correlate their request with the
                    # stored trace (`ray_tpu trace <id>`)
                    self.send_header("traceparent",
                                     tracing.format_traceparent(ctx))
                self._status = code
                self.end_headers()
                self.wfile.write(body)

            def _stream_reply(self, gen, sse: bool = False) -> None:
                """Chunked transfer of a streaming deployment. Two
                framings over the same chunked wire: NDJSON (one JSON
                line per yielded chunk — ref: http_proxy.py:775
                streaming via ASGI) and SSE (`?stream=sse` —
                text/event-stream `data:` frames closed by an
                `event: done` frame, the framing LLM token clients
                expect)."""
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/event-stream" if sse
                                 else "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                if sse:
                    self.send_header("Cache-Control", "no-cache")
                ctx = getattr(self, "_trace_ctx", None)
                if ctx is not None:
                    self.send_header("traceparent",
                                     tracing.format_traceparent(ctx))
                self.end_headers()

                def chunk(b: bytes) -> None:
                    self.wfile.write(f"{len(b):X}\r\n".encode())
                    self.wfile.write(b + b"\r\n")

                def frame(item) -> bytes:
                    body = json.dumps(proxy._jsonable(item)).encode()
                    if sse:
                        return b"data: " + body + b"\n\n"
                    return body + b"\n"

                try:
                    for item in gen:
                        chunk(frame(item))
                except Exception:  # noqa: BLE001
                    # headers are already on the wire: a clean terminator
                    # would present the truncated stream as success, and a
                    # second _reply would corrupt the connection — drop
                    # the connection so the client sees a framing error
                    self.close_connection = True
                    return
                if sse:
                    # explicit terminal frame: SSE clients can't tell a
                    # finished stream from a dropped one without it
                    chunk(b"event: done\ndata: [DONE]\n\n")
                self.wfile.write(b"0\r\n\r\n")

            def _dispatch(self, data) -> None:
                path = urlparse(self.path)
                name = path.path.strip("/")
                q = parse_qs(path.query)
                if name == "-/routes":
                    self._reply(200, proxy._routes())
                    return
                if not name:
                    self._reply(404, {"error": "no deployment in path"})
                    return
                from .handle import extract_session

                # trace ingress: continue the client's W3C traceparent
                # or open a fresh root. The dispatch runs on this
                # handler thread, so activating the contextvar here
                # lets handle._submit capture the context for its
                # router thread; the root span records at the end of
                # the reply (stream included) and completes the trace.
                parent = tracing.parse_traceparent(
                    self.headers.get("traceparent"))
                trace_id = parent[0] if parent else tracing.new_trace_id()
                self._trace_ctx = (trace_id, tracing.new_span_id())
                self._status = 200
                t0 = time.time()
                err = ""
                sess = ""
                stream_mode = "0"
                token = tracing.activate(self._trace_ctx)
                try:
                    h = proxy._get_handle(name)
                    mux = (q.get("model_id") or [""])[0]
                    # session-aware routing: multi-turn conversations
                    # stick to the replica holding their prefix KV
                    sess = extract_session(q, data)
                    stream_mode = (q.get("stream") or ["0"])[0]
                    if stream_mode in ("1", "true", "sse"):
                        gen = h.options(stream=True,
                                        multiplexed_model_id=mux,
                                        session_id=sess).remote(data)
                        self._stream_reply(gen, sse=stream_mode == "sse")
                    else:
                        if mux or sess:
                            h = h.options(multiplexed_model_id=mux,
                                          session_id=sess)
                        ref = h.remote(data)
                        result = ray_tpu.get(ref, timeout=60)
                        self._reply(200, proxy._jsonable(result))
                except Exception as e:  # noqa: BLE001 — surfaced as 500
                    err = f"{type(e).__name__}: {e}"
                    self._reply(500, {"error": err})
                finally:
                    tracing.deactivate(token)
                    tracing.record_span(
                        "http.request",
                        (trace_id, parent[1] if parent else None), t0,
                        span_id=self._trace_ctx[1], ingress=True,
                        deployment=name, session=sess,
                        stream=stream_mode not in ("0", ""),
                        status=self._status, error=err)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    data = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    self._reply(400, {"error": "body must be JSON"})
                    return
                self._dispatch(data)

            def do_GET(self):  # noqa: N802
                from .handle import PROXY_CONTROL_PARAMS

                q = parse_qs(urlparse(self.path).query)
                data = {k: v[0] if len(v) == 1 else v for k, v in q.items()
                        if k not in PROXY_CONTROL_PARAMS}
                self._dispatch(data or None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()

    def _get_handle(self, name: str):
        from .handle import DeploymentHandle

        h = self._handles.get(name)
        if h is None:
            h = self._handles[name] = DeploymentHandle(name)
        return h

    def _routes(self) -> dict:
        from .controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return {"deployments":
                ray_tpu.get(controller.list_deployments.remote(), timeout=10)}

    @staticmethod
    def _jsonable(value):
        import numpy as np

        if isinstance(value, np.ndarray):
            return value.tolist()
        if isinstance(value, (np.integer, np.floating)):
            return value.item()
        return value

    def address(self) -> tuple:
        return ("127.0.0.1", self._port)

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> bool:
        self._server.shutdown()
        return True
