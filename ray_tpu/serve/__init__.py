"""ray_tpu.serve — model serving with a reconciling control plane.

Equivalent of Ray Serve (ref: python/ray/serve/): a detached controller
actor reconciles target vs running replicas (health checks, rolling
updates, request-based autoscaling), DeploymentHandles route with
power-of-two-choices, an HTTP proxy serves JSON ingress, and
MeshDeployment hosts pjit-sharded models on gangs of mesh workers.

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, request): ...

    handle = serve.run(Model.bind(arg))
    result = ray_tpu.get(handle.remote(payload))
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu

from .batching import batch  # noqa: F401 — serve.batch decorator
from .config import AutoscalingConfig, DeploymentConfig
from .controller import CONTROLLER_NAME, get_or_create_controller
from .handle import DeploymentHandle
from .mesh_replica import MeshDeployment
from .multiplex import (get_multiplexed_model_id,  # noqa: F401
                        multiplexed)

__all__ = [
    "AutoscalingConfig", "Application", "Deployment", "DeploymentHandle",
    "MeshDeployment", "delete", "deployment", "get_deployment_handle",
    "get_multiplexed_model_id", "llm", "multiplexed", "run", "shutdown",
    "start_grpc_proxy", "start_http_proxy", "status",
]


def __getattr__(name):
    # serve.llm pulls in jax + the model zoo; load it lazily so plain
    # serve users (and the controller actor) never pay that import
    if name == "llm":
        import importlib

        return importlib.import_module(".llm", __name__)
    raise AttributeError(name)


@dataclass
class Application:
    """A bound deployment (ref: serve/api.py Application / DAG node).
    Nested Applications in args are deployed first and replaced with
    handles — model composition."""
    deployment: "Deployment"
    args: tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)


class Deployment:
    def __init__(self, target: Any, name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None, **kw) -> "Deployment":
        cfg = DeploymentConfig(**{**self.config.__dict__, **kw})
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name!r})"


def deployment(target: Any = None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 8,
               health_check_period_s: float = 2.0,
               health_check_timeout_s: float = 10.0,
               user_config: Any = None,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               slo_target_s: Optional[float] = None):
    """@serve.deployment — class or function (ref: serve/api.py:deployment).

    ``slo_target_s`` sets the deployment's end-to-end latency SLO:
    routed requests count into
    ``ray_tpu_serve_slo_{ok,violated}_total{deployment=...}``."""

    def wrap(t):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            user_config=user_config,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling=autoscaling_config,
            slo_target_s=slo_target_s,
        )
        return Deployment(t, name or t.__name__, cfg)

    return wrap(target) if target is not None else wrap


def _deploy_app(controller, app: Application) -> str:
    # depth-first: nested Applications become handles (model composition)
    def resolve(v):
        if isinstance(v, Application):
            _deploy_app(controller, v)
            return DeploymentHandle(v.deployment.name)
        return v

    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    d = app.deployment
    blob = cloudpickle.dumps(d._target)
    ray_tpu.get(controller.deploy.remote(d.name, blob, args, kwargs,
                                         d.config), timeout=60)
    return d.name


def run(app: Application, *, wait_for_healthy: bool = True,
        timeout: float = 120.0) -> DeploymentHandle:
    """Deploy the application graph; returns the root handle
    (ref: serve/api.py:414 serve.run)."""
    controller = get_or_create_controller()
    root = _deploy_app(controller, app)
    if wait_for_healthy:
        _wait_healthy(controller, root, timeout)
    return DeploymentHandle(root)


def _wait_healthy(controller, name: str, timeout: float) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.status.remote(), timeout=30).get(name)
        if st and st["status"] == "HEALTHY":
            return
        time.sleep(0.1)
    raise TimeoutError(f"deployment {name} not healthy after {timeout}s")


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> Dict[str, dict]:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(controller.status.remote(), timeout=30)


def delete(name: str) -> None:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete.remote(name), timeout=60)


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0) -> tuple:
    """Start the gRPC ingress actor (ref: serve gRPC proxy path);
    returns (host, port). Generic-handler service — see
    serve/grpc_proxy.py for the wire contract."""
    from .grpc_proxy import GrpcProxy

    cls = ray_tpu.remote(GrpcProxy)
    proxy = cls.options(name="SERVE_GRPC_PROXY", lifetime="detached",
                        get_if_exists=True).remote(host, port)
    return tuple(ray_tpu.get(proxy.address.remote(), timeout=30))


def start_http_proxy(host: str = "127.0.0.1", port: int = 0,
                     asyncio_server: bool = True) -> tuple:
    """Start the HTTP ingress actor; returns (host, port). The default is
    the asyncio proxy (http_asyncio.py — the reference's uvicorn/ASGI
    analog); asyncio_server=False keeps the stdlib thread-per-request
    fallback."""
    if asyncio_server:
        from .http_asyncio import AsyncHTTPProxy as ProxyCls
    else:
        from .http_proxy import HTTPProxy as ProxyCls

    cls = ray_tpu.remote(ProxyCls)
    proxy = cls.options(name="SERVE_PROXY", lifetime="detached",
                        get_if_exists=True).remote(host, port)
    return tuple(ray_tpu.get(proxy.address.remote(), timeout=30))


def deploy_config(path: str) -> dict:
    """`serve deploy <config>`: declarative YAML/JSON application config
    (ref: python/ray/serve/schema.py ServeDeploySchema + `serve deploy`).

    Schema:
        http: {host: ..., port: ...}            # optional ingress
        applications:
          - name: my_app                        # optional
            import_path: pkg.module:app         # Application or builder
            args: {...}                         # builder kwargs
            num_replicas: 2                     # per-deployment override

    Returns {"deployments": [names], "http": (host, port) | None}."""
    import importlib

    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    apps = cfg.get("applications") or []
    if not apps:
        raise ValueError(f"{path}: no applications in config")
    deployed = []
    for app_cfg in apps:
        import_path = app_cfg["import_path"]
        mod_name, _, attr = import_path.partition(":")
        if not attr:
            raise ValueError(
                f"import_path must be 'module:attr', got {import_path!r}")
        target = getattr(importlib.import_module(mod_name), attr)
        if callable(target) and not isinstance(target, Application):
            target = target(**(app_cfg.get("args") or {}))
        if not isinstance(target, Application):
            raise TypeError(f"{import_path} is not a serve Application")
        if app_cfg.get("num_replicas"):
            target.deployment.config.num_replicas = int(
                app_cfg["num_replicas"])
        deployed.append(run(target))
    http = cfg.get("http")
    addr = None
    if http is not None:
        addr = start_http_proxy(http.get("host", "127.0.0.1"),
                                int(http.get("port", 8000)))
    return {"deployments": [d._name for d in deployed], "http": addr}


def shutdown() -> None:
    """Tear down every deployment and the controller."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
    for proxy_name in ("SERVE_PROXY", "SERVE_GRPC_PROXY"):
        try:
            proxy = ray_tpu.get_actor(proxy_name)
            ray_tpu.get(proxy.shutdown.remote(), timeout=10)
            ray_tpu.kill(proxy)
        except Exception:
            pass
