"""LLMEngine — continuous (iteration-level) batching over a paged KV cache.

The modern LLM-serving core (vLLM/Orca-style, PAPERS.md) on this
runtime's models: one engine owns a block-pool KV cache
(`model.init_paged_cache`) and runs a scheduler loop where every
iteration (a) admits waiting prompts into the running batch under a
prefill-token budget and the block budget, (b) runs ONE fixed-shape
decode step for every resident sequence, (c) retires finished sequences
(EOS / max_tokens) and frees their blocks, and (d) preempts the
latest-admitted sequence back to the waiting queue when the pool can't
grow a running one — greedy decode makes the requeued continuation
produce exactly the tokens the unpreempted run would have.

XLA compiles a handful of programs, not one per request: decode is a
single `(max_batch,)` program; prefill compiles once per bucket in
`prefill_buckets` (prompts pad up to the nearest bucket).

With ``tp > 1`` the same programs lower under a per-replica device mesh
(parallel.sharding MeshOwner + SpecLayout, docs/SHARDING.md): attention
heads, FFN hidden, and vocab shard on the ``tp`` axis, the paged KV
pool block-shards per chip (BlockPool mirrors the layout and balances
allocation across chips), and greedy decode is token-identical to
tp=1 — the scheduler, streams, and serve integration are unchanged.

    engine = LLMEngine(model, params, EngineConfig(max_batch=8))
    engine.start()                       # background scheduler thread
    stream = engine.add_request([1, 5, 9], max_tokens=32)
    for tok in stream: ...               # sync; `async for` also works

Metrics (OBSERVABILITY.md schema): `ray_tpu_llm_queue_depth`,
`ray_tpu_llm_kv_blocks_used`, `ray_tpu_llm_tokens_per_s` gauges and
`ray_tpu_llm_ttft_seconds` / `ray_tpu_llm_tpot_seconds` histograms, all
tagged by engine name — shipped to the head scrape by the standard
worker delta path and consumed by the serve autoscaler via the
replica's queue_depth (replica.py / controller.py).
"""
from __future__ import annotations

import collections
import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...perf.recorder import get_recorder as _get_recorder
from ...util import metrics as _metrics
from ...util import tracing as _tracing
from .kv_cache import BlockPool, blocks_for_tokens

_FLREC = _get_recorder()

_G_QUEUE = _metrics.Gauge(
    "ray_tpu_llm_queue_depth",
    "LLM engine requests waiting + running", tag_keys=("engine",))
_G_BLOCKS = _metrics.Gauge(
    "ray_tpu_llm_kv_blocks_used",
    "KV-cache pool blocks currently allocated (chip label: per-chip "
    "occupancy of a tp-sharded pool; unlabeled: engine total)",
    tag_keys=("engine", "chip"))
_G_TOKPS = _metrics.Gauge(
    "ray_tpu_llm_tokens_per_s",
    "generated tokens/s over the trailing window", tag_keys=("engine",))
_H_TTFT = _metrics.Histogram(
    "ray_tpu_llm_ttft_seconds",
    "time to first token (submission -> first emit, queue wait included)",
    tag_keys=("engine",))
_H_TPOT = _metrics.Histogram(
    "ray_tpu_llm_tpot_seconds",
    "time per output token during decode (inter-token latency)",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("engine",))
_C_PREFIX_HIT = _metrics.Counter(
    "ray_tpu_llm_prefix_hit_tokens",
    "prompt tokens whose KV came from the radix prefix cache (block-"
    "table reuse, no prefill compute)", tag_keys=("engine",))
_C_PREFIX_MISS = _metrics.Counter(
    "ray_tpu_llm_prefix_miss_tokens",
    "prompt tokens that paid prefill compute (cold or divergent)",
    tag_keys=("engine",))
_G_HIT_RATE = _metrics.Gauge(
    "ray_tpu_llm_cache_hit_rate",
    "cumulative prefix-cache hit rate: hit_tokens / (hit + miss)",
    tag_keys=("engine",))
_C_PREEMPT = _metrics.Counter(
    "ray_tpu_llm_preemptions_total",
    "sequences preempted-and-requeued on KV pool exhaustion",
    tag_keys=("engine",))


@dataclass
class EngineConfig:
    """Scheduler + cache knobs (docs/LLM_SERVE.md)."""
    block_size: int = 16
    num_blocks: int = 128
    max_batch: int = 8                 # decode program batch (slots)
    max_blocks_per_seq: int = 16       # block-table width (M)
    # tensor parallelism: one replica = one mesh spanning tp chips. The
    # prefill/decode programs lower under the mesh with attention heads
    # + FFN sharded on `tp` and the KV pool block-sharded per chip
    # (docs/SHARDING.md); num_blocks must be a multiple of tp
    tp: int = 1
    # prefill-token admission budget per scheduler iteration; at least
    # one waiting request is always admitted so a long prompt can't starve
    max_prefill_tokens_per_step: int = 256
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256)
    eos_id: Optional[int] = None       # engine-wide default EOS
    idle_sleep_s: float = 0.002        # background-loop sleep when idle
    # radix prefix cache (prefix_cache.py, docs/LLM_SERVE.md "Prefix
    # caching & sessions"): retired/preempted sequences leave their
    # full-block prompt+completion KV indexed in a radix tree; a new
    # request reuses the longest cached prefix (refcounted block
    # sharing, copy-on-write at a mid-block divergence) and prefills
    # only its suffix. LRU-evicted under pool pressure. Greedy decode
    # keeps outputs token-identical to cache-off.
    prefix_cache: bool = False

    @property
    def max_context(self) -> int:
        """Longest context a sequence can hold in its block table."""
        return self.max_blocks_per_seq * self.block_size


class TokenStream:
    """Per-request token iterator — sync (`for tok in stream`) and async
    (`async for tok in stream`) views over the same queue. The engine
    pushes tokens as the scheduler emits them; a sentinel closes the
    stream with `finish_reason` in {"eos","length","error"}."""

    _DONE = object()

    def __init__(self, request_id: str):
        self.request_id = request_id
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self._q: "queue.Queue" = queue.Queue()
        self._consumed_done = False

    # engine side ----------------------------------------------------------
    def _put(self, tok: int) -> None:
        self._q.put(tok)

    def _finish(self, reason: str,
                error: Optional[BaseException] = None) -> None:
        self.finish_reason = reason
        self.error = error
        self._q.put(self._DONE)

    # consumer side --------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> int:
        return self.next()

    def next(self, timeout: Optional[float] = 300.0) -> int:
        if self._consumed_done:
            raise StopIteration
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"{self.request_id}: no token within {timeout}s") from None
        if item is self._DONE:
            self._consumed_done = True
            if self.error is not None:
                raise self.error
            raise StopIteration
        return item

    def __aiter__(self):
        return self

    async def __anext__(self) -> int:
        from ..handle import executor_anext

        return await executor_anext(self.next)

    def tokens(self, timeout: Optional[float] = 300.0) -> List[int]:
        """Drain to completion -> the full completion, in order."""
        out = []
        while True:
            try:
                out.append(self.next(timeout=timeout))
            except StopIteration:
                return out


@dataclass
class Request:
    request_id: str
    prompt: List[int]                  # context to (re-)prefill
    max_tokens: int
    eos_id: Optional[int]
    stream: TokenStream
    submitted_at: float
    generated: List[int] = field(default_factory=list)
    first_token_at: Optional[float] = None
    preemptions: int = 0
    # distributed tracing: (trace_id, parent_span_id) captured at
    # add_request — the scheduler thread emits lifecycle spans against
    # it (contextvars can't cross the submit->scheduler thread hop).
    # Wall-clock stamps ride along because Span times are time.time()
    # while the engine's latency math stays on perf_counter.
    trace_ctx: Optional[tuple] = None
    submitted_wall: float = 0.0
    queued_wall: float = 0.0           # last enqueue (submit or preempt)
    cache_hit_tokens: int = 0
    cache_miss_tokens: int = 0


class _Sequence:
    """A running request's batch-slot state."""

    __slots__ = ("req", "slot", "blocks", "seq_len", "pending",
                 "last_emit_at", "tokens", "dec_count", "dec_wall0")

    def __init__(self, req: Request, slot: int, blocks: List[int],
                 seq_len: int, pending: int,
                 tokens: Optional[List[int]] = None):
        self.req = req
        self.slot = slot
        self.blocks = blocks           # pool block ids, table order
        self.seq_len = seq_len         # tokens whose KV is in cache
        self.pending = pending         # emitted token awaiting its KV write
        self.last_emit_at = time.perf_counter()
        self.dec_count = 0             # decode steps since last span flush
        self.dec_wall0 = 0.0
        # the token identity of the resident KV, position by position —
        # what the prefix cache indexes at retire/preempt time
        self.tokens: List[int] = list(tokens if tokens is not None
                                      else req.prompt)


class LLMEngine:
    """One replica's inference engine. Thread-safe: `add_request` may be
    called from any thread; the scheduler runs either on the background
    thread (`start()`) or driven explicitly (`step()` /
    `run_until_idle()` — never both)."""

    _ids = itertools.count()

    def __init__(self, model: Any, params: Dict[str, Any],
                 config: Optional[EngineConfig] = None, name: str = ""):
        import jax

        self.model = model
        self.params = params
        cfg = config or EngineConfig()
        buckets = tuple(sorted(set(
            min(b, cfg.max_context, model.config.max_seq)
            for b in cfg.prefill_buckets)))
        if not buckets:
            raise ValueError("prefill_buckets must be non-empty")
        self.config = cfg
        self.buckets = buckets
        self.max_prompt = buckets[-1]
        # hard context ceiling: the block table AND the model's trained
        # positions — past max_seq the embedding/RoPE gathers clamp
        # under jit and silently reuse the last row
        self.max_seq_len = min(cfg.max_context, model.config.max_seq)
        self.name = name or f"llm-{next(self._ids)}"
        self.tp = int(cfg.tp)
        self.owner = None
        self.pool = BlockPool(cfg.num_blocks, shards=self.tp)
        self._cache = model.init_paged_cache(cfg.num_blocks, cfg.block_size)
        self._cache_sharding = None
        if self.tp > 1:
            # sharded execution layer (docs/SHARDING.md): one mesh per
            # replica; params shard per SpecLayout family (heads/FFN/
            # vocab on tp), the KV pool block-shards per chip, and the
            # host-side scheduler stays unchanged
            from ...parallel.sharding import MeshOwner

            self.owner = MeshOwner.tp_mesh(self.tp,
                                           name=f"llm-{self.name}")
            pspecs = self.owner.layout.param_specs(model)
            self.params = params = {
                n: jax.device_put(v, self.owner.sharding(pspecs[n]))
                for n, v in params.items()}
            self._cache_sharding = self.owner.sharding(
                self.owner.layout.kv_cache_blocks())
            self._cache = {
                k: jax.device_put(v, self._cache_sharding)
                for k, v in self._cache.items()}
        self._lock = threading.RLock()
        self._waiting: "collections.deque[Request]" = collections.deque()
        self._running: List[_Sequence] = []
        self._free_slots = list(range(cfg.max_batch - 1, -1, -1))
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._total_generated = 0
        self._total_preemptions = 0
        # cumulative scheduler-phase seconds; profile() diffs across a
        # window, so these only ever grow
        self._phase_s = {"admit": 0.0, "prefill": 0.0, "decode": 0.0,
                         "retire": 0.0}
        self._prof: Optional[Dict[str, list]] = None
        self._peak_blocks = 0
        self._peak_per_chip: List[int] = [0] * self.tp
        self._tok_events: "collections.deque" = collections.deque()
        self.prefix_cache = None
        self._prefix_hits = 0          # tokens served from cached KV
        self._prefix_misses = 0        # tokens that paid prefill compute
        if cfg.prefix_cache:
            from .prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(self.pool, cfg.block_size)

        # jit entry points; jax caches one compiled program per argument
        # shape, so decode compiles once and prefill (and the suffix
        # extend variant) once per bucket — the buckets BOUND the
        # program count
        def _decode(params, kc, vc, tokens, positions, rows, active):
            logits, cache = model.paged_decode_step(
                params, {"k": kc, "v": vc}, tokens, positions, rows, active)
            return logits, cache["k"], cache["v"]

        def _prefill(params, kc, vc, tokens, length, block_row):
            logits, cache = model.paged_prefill(
                params, {"k": kc, "v": vc}, tokens, length, block_row)
            return logits, cache["k"], cache["v"]

        def _extend(params, kc, vc, tokens, start, length, block_row):
            logits, cache = model.paged_prefill_extend(
                params, {"k": kc, "v": vc}, tokens, start, length,
                block_row)
            return logits, cache["k"], cache["v"]

        def _cow(kc, vc, src, dst):
            # duplicate one pool block (copy-on-write divergence point):
            # block axis is axis 1 of the [L, N, Bs, KH, hd] cache
            return (kc.at[:, dst].set(kc[:, src]),
                    vc.at[:, dst].set(vc[:, src]))

        if self.owner is None:
            self._decode_fn = jax.jit(_decode)
            self._prefill_fn = jax.jit(_prefill)
            self._extend_fn = jax.jit(_extend)
            self._cow_fn = jax.jit(_cow)
        else:
            # pjit plane (sharding/lower.py): GSPMD partitions the body
            # under the replica's mesh. Host-side inputs (tokens/rows/
            # lengths) replicate; logits come back replicated so the
            # scheduler's argmax sees full vocab; the cache stays
            # block-sharded across calls. Decode donates its cache
            # buffers on accelerator backends so the pool updates in
            # place (the forced-host CPU backend has no donation).
            from ...parallel.sharding import lower_jit

            rep = self.owner.layout.replicated()
            kvspec = self.owner.layout.kv_cache_blocks()
            donate = (1, 2) if \
                self.owner.devices[0].platform != "cpu" else ()
            self._decode_fn = lower_jit(
                _decode, self.owner,
                in_specs=(pspecs, kvspec, kvspec, rep, rep, rep, rep),
                out_specs=(rep, kvspec, kvspec),
                donate_argnums=donate)
            self._prefill_fn = lower_jit(
                _prefill, self.owner,
                in_specs=(pspecs, kvspec, kvspec, rep, rep, rep),
                out_specs=(rep, kvspec, kvspec))
            self._extend_fn = lower_jit(
                _extend, self.owner,
                in_specs=(pspecs, kvspec, kvspec, rep, rep, rep, rep),
                out_specs=(rep, kvspec, kvspec))
            self._cow_fn = lower_jit(
                _cow, self.owner,
                in_specs=(kvspec, kvspec, rep, rep),
                out_specs=(kvspec, kvspec))

    # -- request intake -------------------------------------------------------

    def add_request(self, prompt: Sequence[int], max_tokens: int = 16,
                    eos_id: Any = "__default__",
                    request_id: Optional[str] = None,
                    trace_ctx: Optional[tuple] = None) -> TokenStream:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds engine capacity "
                f"{self.max_prompt} (largest prefill bucket)")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        rid = request_id or f"req-{next(self._ids)}"
        stream = TokenStream(rid)
        if trace_ctx is None:
            # the replica activates the request's context around the
            # user-callable invocation, which reaches here synchronously
            trace_ctx = _tracing.current_context()
        now_wall = time.time()
        req = Request(rid, prompt, int(max_tokens),
                      self.config.eos_id if eos_id == "__default__"
                      else eos_id,
                      stream, time.perf_counter(),
                      trace_ctx=tuple(trace_ctx) if trace_ctx else None,
                      submitted_wall=now_wall, queued_wall=now_wall)
        with self._lock:
            self._waiting.append(req)
            self._update_gauges()
        return stream

    def add_prefilled(self, prompt: Sequence[int], kv_blocks: Dict[str, Any],
                      first_token: int, max_tokens: int = 16,
                      eos_id: Any = "__default__",
                      timeout: float = 60.0) -> TokenStream:
        """Disaggregated-prefill intake: the prompt's KV was computed by a
        prefill stage (disagg.py) and arrives as block-shaped arrays
        k/v [L, nb, block_size, KH, hd]; this engine copies them into
        freshly allocated pool blocks and the sequence enters decode
        directly — no local prefill pass."""
        import jax.numpy as jnp

        prompt = [int(t) for t in prompt]
        nb = int(kv_blocks["k"].shape[1])
        if nb != blocks_for_tokens(len(prompt), self.config.block_size):
            raise ValueError(
                f"shipped {nb} blocks for a {len(prompt)}-token prompt "
                f"(block_size {self.config.block_size})")
        rid = f"req-{next(self._ids)}"
        stream = TokenStream(rid)
        trace_ctx = _tracing.current_context()
        now_wall = time.time()
        req = Request(rid, prompt, int(max_tokens),
                      self.config.eos_id if eos_id == "__default__"
                      else eos_id,
                      stream, time.perf_counter(),
                      trace_ctx=tuple(trace_ctx) if trace_ctx else None,
                      submitted_wall=now_wall, queued_wall=now_wall)
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                # evicting alloc: a prefix-cached decode stage would
                # otherwise wedge once rc-1 cache residency drains the
                # free list (nothing here runs _admit's eviction path)
                blocks = self._alloc_with_evict(nb)
                slot = self._free_slots.pop() if (
                    blocks is not None and self._free_slots) else None
                if blocks is not None and slot is None:
                    self.pool.free(blocks)
                    blocks = None
                if blocks is not None:
                    idx = jnp.asarray(blocks, jnp.int32)
                    self._cache = {
                        "k": self._cache["k"].at[:, idx].set(
                            jnp.asarray(kv_blocks["k"],
                                        self._cache["k"].dtype)),
                        "v": self._cache["v"].at[:, idx].set(
                            jnp.asarray(kv_blocks["v"],
                                        self._cache["v"].dtype)),
                    }
                    if self._cache_sharding is not None:
                        # the host-side scatter above runs outside the
                        # lowered programs and may leave the result on
                        # GSPMD's preferred layout — pin it back to the
                        # block-sharded residence the decode program
                        # expects
                        import jax as _jax

                        self._cache = {
                            k: _jax.device_put(v, self._cache_sharding)
                            for k, v in self._cache.items()}
                    seq = _Sequence(req, slot, blocks, len(prompt),
                                    int(first_token))
                    self._running.append(seq)
                    req.first_token_at = time.perf_counter()
                    _H_TTFT.observe(req.first_token_at - req.submitted_at,
                                    tags={"engine": self.name},
                                    exemplar=req.trace_ctx[0]
                                    if req.trace_ctx else None)
                    if req.trace_ctx is not None:
                        # disagg intake: prefill happened remotely (on
                        # the SAME trace via the shipped trace_ctx)
                        _tracing.record_span(
                            "llm.admit", req.trace_ctx, req.queued_wall,
                            request_id=req.request_id, engine=self.name,
                            prompt=len(prompt), disagg=True)
                    self._emit(seq, int(first_token), decode_step=False)
                    self._update_gauges()
                    return stream
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{self.name}: no capacity for prefilled sequence "
                    f"({nb} blocks) after {timeout}s")
            time.sleep(0.005)

    # -- scheduler ------------------------------------------------------------

    def step(self) -> bool:
        """One scheduler iteration: retire/admit/decode. Returns True if
        any work was done (callers can sleep when False)."""
        with self._lock:
            ph = self._phase_s
            p0, r0 = ph["prefill"], ph["retire"]
            t0 = time.perf_counter()
            admitted = self._admit()
            t1 = time.perf_counter()
            r1 = ph["retire"]
            decoded = self._decode_iteration()
            t2 = time.perf_counter()
            # admit = scheduling overhead net of the prefill compute and
            # any retires it triggered (both self-accumulate); decode
            # likewise nets out retires
            ph["admit"] += max(0.0, (t1 - t0) - (ph["prefill"] - p0)
                               - (r1 - r0))
            ph["decode"] += max(0.0, (t2 - t1) - (ph["retire"] - r1))
            if self._prof is not None and (admitted or decoded):
                self._prof["occupancy"].append(float(len(self._running)))
                self._prof["kv_pressure"].append(round(
                    self.pool.used_count / self.pool.num_blocks, 4))
                self._prof["step_ms"].append(round((t2 - t0) * 1e3, 4))
            self._update_gauges()
            return admitted or decoded

    def _alloc_with_evict(self, n: int) -> Optional[List[int]]:
        """Pool alloc that spends cached prefixes under pressure: when
        the free list can't cover ``n``, LRU-evict refcount-1 cache
        nodes until it can (cache residency is a best-effort use of idle
        blocks, never a reason to preempt live work)."""
        blocks = self.pool.alloc(n)
        if blocks is None and self.prefix_cache is not None:
            short = n - self.pool.free_count
            if short > 0:
                self.prefix_cache.evict(short)
            blocks = self.pool.alloc(n)
        return blocks

    def _admit(self) -> bool:
        cfg = self.config
        budget = cfg.max_prefill_tokens_per_step
        admitted = False
        while self._waiting and self._free_slots:
            req = self._waiting[0]
            p = len(req.prompt)
            if p > self.max_prompt:
                # grew past capacity through preemption requeues
                self._waiting.popleft()
                req.stream._finish("error", RuntimeError(
                    f"{req.request_id}: context {p} exceeds engine "
                    f"capacity {self.max_prompt}"))
                continue
            # longest cached prefix (at most p-1: the last prompt token
            # always prefills so its logits pick the first new token)
            match = None
            cached = 0
            if self.prefix_cache is not None:
                match = self.prefix_cache.match(req.prompt[:-1])
                cached = match.num_tokens + match.partial_len
            if admitted and p - cached > budget:
                break                     # token budget for this iteration
            nb = blocks_for_tokens(p, cfg.block_size)
            reused = list(match.blocks) if match else []
            # pin the matched blocks (and the COW source) before any
            # eviction the alloc below may trigger can free them; the
            # pin rides match.blocks into the sequence's table and is
            # freed at retire/preempt through seq.blocks
            if reused:
                self.pool.retain(reused)  # graftcheck: disable=GC030
            if match is not None and match.partial_block is not None:
                self.pool.retain([match.partial_block])
            blocks = self._alloc_with_evict(nb - len(reused))
            if blocks is None:
                if reused:
                    self.pool.free(reused)
                if match is not None and match.partial_block is not None:
                    self.pool.free([match.partial_block])
                if not self._running and nb > self.pool.num_blocks:
                    self._waiting.popleft()
                    req.stream._finish("error", RuntimeError(
                        f"{req.request_id}: prompt needs {nb} blocks; "
                        f"pool holds {self.pool.num_blocks}"))
                    continue
                if not self._running and self.prefix_cache is not None \
                        and self.prefix_cache.resident_blocks:
                    # nothing running will ever free blocks, and partial
                    # matches can pin nodes eviction must skip: drop the
                    # whole cache and retry cold — progress beats warmth
                    self.prefix_cache.clear()
                    continue
                break                     # wait for decode frees/preemption
            self._waiting.popleft()
            budget -= p - cached
            admitted = True
            tp0 = time.perf_counter()
            tw0 = time.time()
            if cached:
                self._prefill_cached(req, match, blocks)
            else:
                self._prefill_into(req, blocks)
            self._phase_s["prefill"] += time.perf_counter() - tp0
            if req.trace_ctx is not None:
                _tracing.record_span(
                    "llm.prefill", req.trace_ctx, tw0,
                    request_id=req.request_id, engine=self.name,
                    tokens=p - cached, cached_tokens=cached)
        return admitted

    def _prefill_into(self, req: Request, blocks: List[int]) -> None:
        import jax.numpy as jnp

        cfg = self.config
        p = len(req.prompt)
        bucket = next(b for b in self.buckets if b >= p)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :p] = req.prompt
        row = np.full((cfg.max_blocks_per_seq,), -1, np.int32)
        row[:len(blocks)] = blocks
        logits, kc, vc = self._prefill_fn(
            self.params, self._cache["k"], self._cache["v"],
            jnp.asarray(toks), jnp.int32(p), jnp.asarray(row))
        self._cache = {"k": kc, "v": vc}
        first = int(np.asarray(logits).argmax())
        self._count_prefix(0, p)
        req.cache_hit_tokens, req.cache_miss_tokens = 0, p
        self._start_sequence(req, blocks, p, first)

    def _prefill_cached(self, req: Request, match, blocks: List[int]) -> None:
        """Suffix-only prefill over a matched cached prefix: the
        sequence's table is [reused full blocks | fresh blocks]; a
        mid-block divergence first duplicates the partially-shared block
        into the first fresh one (COW), then only prompt[cached:] runs
        through the extend program — the dominant cost of a shared-
        prefix request becomes this block-table splice."""
        import jax.numpy as jnp

        cfg = self.config
        p = len(req.prompt)
        cached = match.num_tokens + match.partial_len
        table = list(match.blocks) + blocks
        if match.partial_len:
            # COW at the divergence point: blocks[0] becomes this
            # sequence's private copy of the partially-shared block
            kc, vc = self._cow_fn(
                self._cache["k"], self._cache["v"],
                jnp.int32(match.partial_block), jnp.int32(blocks[0]))
            self._cache = {"k": kc, "v": vc}
            # the pin taken at match time was only for the copy
            self.pool.free([match.partial_block])
        suffix = req.prompt[cached:]
        s = len(suffix)
        bucket = next(b for b in self.buckets if b >= s)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :s] = suffix
        row = np.full((cfg.max_blocks_per_seq,), -1, np.int32)
        row[:len(table)] = table
        logits, kc, vc = self._extend_fn(
            self.params, self._cache["k"], self._cache["v"],
            jnp.asarray(toks), jnp.int32(cached), jnp.int32(s),
            jnp.asarray(row))
        self._cache = {"k": kc, "v": vc}
        first = int(np.asarray(logits).argmax())
        self._count_prefix(cached, s)
        req.cache_hit_tokens, req.cache_miss_tokens = cached, s
        self._start_sequence(req, table, p, first)

    def _start_sequence(self, req: Request, blocks: List[int], p: int,
                        first: int) -> None:
        slot = self._free_slots.pop()
        seq = _Sequence(req, slot, blocks, p, first)
        self._running.append(seq)
        if _FLREC.enabled:
            _FLREC.record("llm.admit", req.request_id,
                          {"engine": self.name, "prompt": p, "slot": slot})
        if self.prefix_cache is not None:
            # index the prompt's full blocks NOW so concurrent requests
            # sharing the prefix hit before this sequence even retires
            self.prefix_cache.insert(seq.tokens, seq.blocks)
        now = time.perf_counter()
        if req.first_token_at is None:
            req.first_token_at = now
            _H_TTFT.observe(now - req.submitted_at,
                            tags={"engine": self.name},
                            exemplar=req.trace_ctx[0]
                            if req.trace_ctx else None)
        if req.trace_ctx is not None:
            # queue wait + prefill, with the prefix-cache outcome as
            # attributes (hit tokens reused KV; miss tokens paid compute)
            _tracing.record_span(
                "llm.admit", req.trace_ctx, req.queued_wall,
                request_id=req.request_id, engine=self.name,
                prompt=p, slot=seq.slot, preemptions=req.preemptions,
                cache_hit_tokens=req.cache_hit_tokens,
                cache_miss_tokens=req.cache_miss_tokens)
        self._emit(seq, first, decode_step=False)

    def _count_prefix(self, hit: int, miss: int) -> None:
        if self.prefix_cache is None:
            return
        tags = {"engine": self.name}
        if hit:
            self._prefix_hits += hit
            _C_PREFIX_HIT.inc(hit, tags=tags)
        if miss:
            self._prefix_misses += miss
            _C_PREFIX_MISS.inc(miss, tags=tags)

    def _decode_iteration(self) -> bool:
        cfg = self.config
        if not self._running:
            return False
        # grow block tables for this iteration's writes; preempt the
        # latest-admitted sequence when the pool is out of blocks
        i = 0
        while i < len(self._running):
            seq = self._running[i]
            # this iteration writes the pending token at position
            # seq_len, so the context must still have room for it
            if seq.seq_len >= self.max_seq_len:
                self._retire(seq, "length")
                continue
            need = seq.seq_len // cfg.block_size + 1
            if need > cfg.max_blocks_per_seq:
                self._retire(seq, "length")
                continue
            grow = need - len(seq.blocks)
            # a shared block under the write position must be
            # duplicated before this sequence extends it (COW): decode
            # structurally writes only private tail blocks, but a
            # refcount > 1 here — however it arose — would corrupt
            # every other holder's context
            wi = seq.seq_len // cfg.block_size
            cow = (grow <= 0 and self.prefix_cache is not None
                   and self.pool.refcount(seq.blocks[wi]) > 1)
            if grow > 0 or cow:
                got = self._alloc_with_evict(max(grow, 0) + int(cow))
                if got is None:
                    victim = self._running[-1]
                    if victim is seq and len(self._running) == 1:
                        if self.prefix_cache is not None and \
                                self.prefix_cache.resident_blocks:
                            # partially-shared nodes can pin blocks LRU
                            # eviction must skip — drop the whole cache
                            # before declaring the pool exhausted.
                            # Clearing may also drop the only other
                            # reference on the write block: recompute
                            # cow so the rescue doesn't pay a pointless
                            # device copy
                            self.prefix_cache.clear()
                            cow = (grow <= 0 and self.pool.refcount(
                                seq.blocks[wi]) > 1)
                            got = self.pool.alloc(max(grow, 0) + int(cow))
                        if got is None:
                            # sole runner and the pool still can't grow
                            # it: blocks are held outside this engine —
                            # fail loud
                            self._retire(seq, "error", RuntimeError(
                                f"{seq.req.request_id}: KV pool exhausted "
                                f"with no preemptible sequence"))
                            continue
                    else:
                        self._preempt(victim)
                        if victim is seq:
                            continue      # seq left the running list
                        continue          # retry the same seq
                if cow:
                    self._cow_block(seq, wi, got.pop())
                seq.blocks.extend(got)
            i += 1
        if not self._running:
            return False
        import jax.numpy as jnp

        b, m = cfg.max_batch, cfg.max_blocks_per_seq
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        rows = np.full((b, m), -1, np.int32)
        active = np.zeros((b,), bool)
        for seq in self._running:
            tokens[seq.slot] = seq.pending
            positions[seq.slot] = seq.seq_len
            rows[seq.slot, :len(seq.blocks)] = seq.blocks
            active[seq.slot] = True
        logits, kc, vc = self._decode_fn(
            self.params, self._cache["k"], self._cache["v"],
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(rows), jnp.asarray(active))
        self._cache = {"k": kc, "v": vc}
        arr = np.asarray(logits)
        emitted = 0
        for seq in list(self._running):
            seq.seq_len += 1              # pending's KV landed this step
            seq.tokens.append(seq.pending)
            tok = int(arr[seq.slot].argmax())
            seq.pending = tok
            self._emit(seq, tok, decode_step=True)
            emitted += 1
        now = time.perf_counter()
        self._tok_events.append((now, emitted))
        self._total_generated += emitted
        return True

    # decode spans aggregate: one span per this many steps, not one per
    # token — span traffic stays O(tokens/32) while the trace still
    # shows decode progress and inter-span gaps
    _DECODE_SPAN_STEPS = 32

    def _flush_decode_span(self, seq: "_Sequence") -> None:
        if seq.dec_count and seq.req.trace_ctx is not None:
            _tracing.record_span(
                "llm.decode", seq.req.trace_ctx, seq.dec_wall0,
                request_id=seq.req.request_id, engine=self.name,
                tokens=seq.dec_count)
        seq.dec_count = 0

    def _emit(self, seq: _Sequence, tok: int, decode_step: bool) -> None:
        req = seq.req
        now = time.perf_counter()
        if decode_step:
            _H_TPOT.observe(now - seq.last_emit_at,
                            tags={"engine": self.name},
                            exemplar=req.trace_ctx[0]
                            if req.trace_ctx else None)
            if req.trace_ctx is not None:
                if seq.dec_count == 0:
                    seq.dec_wall0 = time.time()
                seq.dec_count += 1
                if seq.dec_count >= self._DECODE_SPAN_STEPS:
                    self._flush_decode_span(seq)
        seq.last_emit_at = now
        req.generated.append(tok)
        req.stream._put(tok)
        if req.eos_id is not None and tok == req.eos_id:
            self._retire(seq, "eos")
        elif len(req.generated) >= req.max_tokens:
            self._retire(seq, "length")

    def _cow_block(self, seq: _Sequence, wi: int, fresh: int) -> None:
        """Copy-on-write: duplicate seq.blocks[wi] into ``fresh`` on
        device, swap the table entry, release this sequence's reference
        on the shared original."""
        import jax.numpy as jnp

        kc, vc = self._cow_fn(self._cache["k"], self._cache["v"],
                              jnp.int32(seq.blocks[wi]), jnp.int32(fresh))
        self._cache = {"k": kc, "v": vc}
        self.pool.free([seq.blocks[wi]])
        seq.blocks[wi] = fresh

    def _retire(self, seq: _Sequence, reason: str,
                error: Optional[BaseException] = None) -> None:
        t0 = time.perf_counter()
        self._running.remove(seq)
        if self.prefix_cache is not None and error is None:
            # leave the full-block KV of prompt+completion behind for
            # followers (multi-turn sessions re-send this context); the
            # cache takes its own references, so the free below releases
            # only this sequence's claim
            self.prefix_cache.insert(seq.tokens, seq.blocks)
        self.pool.free(seq.blocks)
        self._free_slots.append(seq.slot)
        if _FLREC.enabled:
            _FLREC.record("llm.retire", seq.req.request_id,
                          {"engine": self.name, "reason": reason,
                           "generated": len(seq.req.generated)})
        req = seq.req
        if req.trace_ctx is not None:
            self._flush_decode_span(seq)
            _tracing.record_span(
                "llm.retire", req.trace_ctx, req.submitted_wall,
                request_id=req.request_id, engine=self.name,
                reason=reason, generated=len(req.generated),
                preemptions=req.preemptions,
                cache_hit_tokens=req.cache_hit_tokens,
                cache_miss_tokens=req.cache_miss_tokens,
                error=type(error).__name__ if error is not None else "")
        seq.req.stream._finish(reason, error)
        self._phase_s["retire"] += time.perf_counter() - t0

    def _preempt(self, seq: _Sequence) -> None:
        """Free everything the sequence holds and requeue it at the front
        of the waiting queue with prompt = full context so far; greedy
        re-prefill continues the exact token sequence (and, with the
        prefix cache on, mostly re-uses its own still-cached KV — the
        private tail is the only real loss)."""
        self._running.remove(seq)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(seq.tokens, seq.blocks)
        self.pool.free(seq.blocks)
        self._free_slots.append(seq.slot)
        req = seq.req
        # full context to re-prefill = what this run prefilled plus every
        # token it generated (seq_len - prefill_len KV writes + pending)
        n_new = seq.seq_len - len(req.prompt) + 1
        req.prompt = list(req.prompt) + req.generated[-n_new:]
        req.preemptions += 1
        self._total_preemptions += 1
        _C_PREEMPT.inc(tags={"engine": self.name})
        if _FLREC.enabled:
            _FLREC.record("llm.preempt", req.request_id,
                          {"engine": self.name,
                           "context": len(req.prompt)})
        if req.trace_ctx is not None:
            self._flush_decode_span(seq)
            now_w = time.time()
            # the trace store always tail-keeps traces with this span
            _tracing.record_span(
                "llm.preempt", req.trace_ctx, now_w, end=now_w,
                request_id=req.request_id, engine=self.name,
                context=len(req.prompt), preemptions=req.preemptions)
            req.queued_wall = now_w
        self._waiting.appendleft(req)

    # -- loop drivers ---------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"llm-engine-{self.name}")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self.step()
            except Exception as e:  # noqa: BLE001 — fail every stream loud
                self._fail_all(e)
                worked = False
            if not worked:
                self._stop.wait(self.config.idle_sleep_s)

    def _fail_all(self, error: BaseException) -> None:
        try:
            from ...perf.postmortem import dump_bundle

            # advisory queue depths for the crash report: stale is fine
            # graftcheck: disable=GC050
            waiting = len(self._waiting)
            # graftcheck: disable=GC050
            running = len(self._running)
            dump_bundle(f"llm engine poisoned: {error!r}",
                        origin=f"llm:{self.name}",
                        meta={"engine": self.name,
                              "waiting": waiting,
                              "running": running})
        except Exception:
            pass
        with self._lock:
            for seq in list(self._running):
                self._retire(seq, "error", error)
            while self._waiting:
                self._waiting.popleft().stream._finish("error", error)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def is_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def run_until_idle(self, timeout: float = 300.0) -> None:
        """Drive the scheduler inline until no request is waiting or
        running (bench/test mode; don't mix with start())."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                idle = not self._waiting and not self._running
            if idle:
                return
            self.step()
            if time.monotonic() > deadline:
                raise TimeoutError(f"{self.name}: not idle after {timeout}s")

    # -- introspection --------------------------------------------------------

    def profile(self, steps: int = 8, flops_per_token: Optional[float] = None,
                peak_flops: Optional[float] = None, timeout: float = 60.0):
        """Profile ``steps`` scheduler iterations and return a
        :class:`ray_tpu.perf.StepReport` (kind="llm") with the
        admit/prefill/decode/retire phase split, batch-occupancy and
        KV-pressure series, tokens/s and MFU.

        With the background thread running (``start()``) this observes
        passively until ``steps`` working iterations elapsed; otherwise
        it drives ``step()`` inline over whatever is queued.
        ``flops_per_token`` defaults to ``model.flops_per_token()`` when
        the model has one; ``peak_flops`` to ``RAY_TPU_PEAK_FLOPS``."""
        from ...perf.report import StepReport

        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        if peak_flops is None:
            peak_flops = float(os.environ.get("RAY_TPU_PEAK_FLOPS", 0))
        if flops_per_token is None:
            fpt = getattr(self.model, "flops_per_token", None)
            flops_per_token = float(fpt()) if callable(fpt) else 0.0
        with self._lock:
            self._prof = {"occupancy": [], "kv_pressure": [],
                          "step_ms": []}
            base = dict(self._phase_s)
            gen0 = self._total_generated
        t_start = time.time()
        wall0 = time.perf_counter()
        try:
            if self.is_alive():
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    with self._lock:
                        if len(self._prof["step_ms"]) >= steps:
                            break
                    time.sleep(0.003)
            else:
                for _ in range(steps):
                    self.step()
        finally:
            wall_s = time.perf_counter() - wall0
            with self._lock:
                prof, self._prof = self._prof, None
                phases = {k: round((self._phase_s[k] - base[k]) * 1e3, 3)
                          for k in base}
                gen = self._total_generated - gen0
        events = [ev for ev in _FLREC.snapshot(clear=False)
                  if ev["ts"] >= t_start][-2000:]
        return StepReport(
            kind="llm", engine=self.name, steps=len(prof["step_ms"]),
            wall_s=wall_s, step_ms=prof["step_ms"], phases=phases,
            tokens=float(gen),
            tokens_per_s=gen / wall_s if gen and wall_s > 0 else 0.0,
            flops_per_token=flops_per_token, peak_flops=peak_flops,
            occupancy=prof["occupancy"], kv_pressure=prof["kv_pressure"],
            events=events,
            extra={"max_batch": self.config.max_batch,
                   "num_blocks": self.config.num_blocks,
                   "preemptions": self._total_preemptions})

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._waiting) + len(self._running)

    def _tokens_per_s(self, window_s: float = 10.0) -> float:
        now = time.perf_counter()
        while self._tok_events and now - self._tok_events[0][0] > window_s:
            self._tok_events.popleft()
        if len(self._tok_events) < 2:
            return 0.0
        span = now - self._tok_events[0][0]
        return sum(n for _, n in self._tok_events) / max(span, 1e-6)

    def _update_gauges(self) -> None:
        tags = {"engine": self.name}
        _G_QUEUE.set(len(self._waiting) + len(self._running), tags=tags)
        # used_count counts shared blocks ONCE (refcounted pool), so
        # this gauge can never report occupancy above pool capacity
        _G_BLOCKS.set(self.pool.used_count, tags=tags)
        _G_TOKPS.set(round(self._tokens_per_s(), 1), tags=tags)
        if self.prefix_cache is not None:
            seen = self._prefix_hits + self._prefix_misses
            _G_HIT_RATE.set(
                round(self._prefix_hits / seen, 4) if seen else 0.0,
                tags=tags)
        self._peak_blocks = max(self._peak_blocks, self.pool.used_count)
        if self.tp > 1:
            for chip, used in enumerate(self.pool.used_per_shard()):
                _G_BLOCKS.set(used, tags={"engine": self.name,
                                          "chip": str(chip)})
                self._peak_per_chip[chip] = max(
                    self._peak_per_chip[chip], used)

    def kv_bytes_per_chip(self) -> Dict[int, int]:
        """Resident KV-cache bytes per CHIP — keyed by mesh position
        0..tp-1 (same keying as the pool's shard accounting and the
        `{chip=}` gauge; raw jax device ids are global on multi-host
        TPUs and would not line up)."""
        with self._lock:  # metrics thread: the step loop mutates _cache
            cache = dict(self._cache)
        if self.owner is None:
            total = sum(int(np.asarray(v).nbytes)
                        for v in cache.values())
            return {0: total}
        by_dev = self.owner.per_device_bytes(cache)
        return {chip: by_dev.get(d.id, 0)
                for chip, d in enumerate(self.owner.devices)}

    def cache_stats(self) -> Dict[str, Any]:
        """Prefix-cache health — the replica ships this in its health
        ping (replica.py) so the controller/balancer can prefer
        cache-warm replicas. All zeros with the cache disabled."""
        with self._lock:
            hit, miss = self._prefix_hits, self._prefix_misses
            pc = self.prefix_cache
            return {
                "cache_hit_rate": round(hit / (hit + miss), 4)
                if hit + miss else 0.0,
                "prefix_hit_tokens": hit,
                "prefix_miss_tokens": miss,
                "prefix_blocks_resident": pc.resident_blocks if pc else 0,
                "prefix_nodes": pc.num_nodes if pc else 0,
                "prefix_evictions": pc.evictions if pc else 0,
            }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "engine": self.name,
                "waiting": len(self._waiting),
                "running": len(self._running),
                "queue_depth": len(self._waiting) + len(self._running),
                "kv_blocks_used": self.pool.used_count,
                "kv_blocks_total": self.pool.num_blocks,
                "kv_occupancy": round(
                    self.pool.used_count / self.pool.num_blocks, 4),
                "tokens_per_s": round(self._tokens_per_s(), 1),
                "total_generated": self._total_generated,
                "preemptions": self._total_preemptions,
                "tp": self.tp,
                "kv_blocks_peak": self._peak_blocks,
            }
            if self.prefix_cache is not None:
                out.update(self.cache_stats())
            if self.tp > 1:
                out["kv_blocks_per_chip"] = self.pool.used_per_shard()
                out["kv_blocks_peak_per_chip"] = list(self._peak_per_chip)
                out["kv_bytes_per_chip"] = {
                    str(d): b for d, b in self.kv_bytes_per_chip().items()}
            return out
