"""ray_tpu.serve.llm — continuous-batching LLM inference engine.

The Serve-side LLM core (ROADMAP item 1): a paged KV cache over a
preallocated block pool (kv_cache.py + ops/paged_attention.py), an
iteration-level scheduler that admits prefills into running decode
batches under token/block budgets and preempts-and-requeues on
allocation failure (engine.py), a serve deployment with streaming token
responses (deployment.py), an optional disaggregated prefill/decode
mode over compiled-graph channels (disagg.py), and zero-loss replica
failover for token streams (failover.py — streamed tokens become the
forced prefix of a re-prefill on a surviving replica). See
docs/LLM_SERVE.md and docs/FAULT_TOLERANCE.md.
"""
from .deployment import LLMServer, build_model
from .disagg import DecodeStage, DisaggLLM, PrefillStage
from .engine import EngineConfig, LLMEngine, Request, TokenStream
from .failover import llm_resume, resilient_stream
from .kv_cache import BlockPool, blocks_for_tokens
from .prefix_cache import PrefixCache, PrefixMatch

__all__ = [
    "BlockPool", "DecodeStage", "DisaggLLM", "EngineConfig", "LLMEngine",
    "LLMServer", "PrefillStage", "PrefixCache", "PrefixMatch", "Request",
    "TokenStream", "build_model", "blocks_for_tokens", "llm_resume",
    "resilient_stream",
]
