"""Disaggregated prefill/decode — stage actors wired by a compiled graph.

Prefill (compute-bound, batch-1 bucketed forward) and decode
(bandwidth-bound, iteration-batched) have opposite hardware profiles;
serving systems split them across accelerator pools. Here the split is a
two-stage cgraph pipeline: a PrefillStage actor computes a prompt's KV
into block-shaped arrays and ships them over the pre-allocated cgraph
channel to a DecodeStage actor, whose engine adopts the blocks
(`LLMEngine.add_prefilled`) and streams out the completion — the decode
loop never pays a prefill pass, and the shipped tensors ride the PR 4
channel machinery instead of per-call RPC.

    llm = DisaggLLM(model="gpt-tiny")
    try:
        out = llm.generate([1, 5, 9], max_tokens=16)
    finally:
        llm.shutdown()

Both stage methods are pure compute (no dynamic .remote()/get inside the
bound methods — the GC008 contract for compiled-graph actors).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .engine import EngineConfig, LLMEngine
from .kv_cache import blocks_for_tokens


class PrefillStage:
    """Computes prompt KV as pool-block-shaped arrays. Bound into the
    cgraph as stage 1."""

    def __init__(self, model: Any = "gpt-tiny", block_size: int = 16,
                 buckets: tuple = (16, 32, 64, 128), seed: int = 0):
        import functools

        import jax

        from .deployment import build_model

        self.model, self.params = build_model(model, seed=seed)
        self.block_size = int(block_size)
        self.buckets = tuple(sorted(buckets))

        @functools.partial(jax.jit)
        def _prefill(params, kc, vc, tokens, length, row):
            logits, cache = self.model.paged_prefill(
                params, {"k": kc, "v": vc}, tokens, length, row)
            return logits, cache["k"], cache["v"]

        self._prefill_fn = _prefill

    def prefill(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """payload {"tokens": [...], ...} -> the wire record for
        DecodeStage.ingest: prompt, first token, and the KV blocks."""
        prompt = [int(t) for t in payload["tokens"]]
        p = len(prompt)
        bucket = next(b for b in self.buckets if b >= p)
        nb = blocks_for_tokens(p, self.block_size)
        # a throwaway pool sized exactly for this prompt: blocks 0..nb-1
        cache = self.model.init_paged_cache(nb, self.block_size)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :p] = prompt
        row = np.full((max(nb, 1),), -1, np.int32)
        row[:nb] = np.arange(nb)
        import jax.numpy as jnp

        logits, kc, vc = self._prefill_fn(
            self.params, cache["k"], cache["v"], jnp.asarray(toks),
            jnp.int32(p), jnp.asarray(row))
        return {
            "prompt": prompt,
            "first_token": int(np.asarray(logits).argmax()),
            "kv": {"k": np.asarray(kc), "v": np.asarray(vc)},
            "max_tokens": int(payload.get("max_tokens", 16)),
            "eos_id": payload.get("eos_id", "__default__"),
        }


class DecodeStage:
    """Adopts shipped KV blocks and decodes to completion. Bound into
    the cgraph as stage 2."""

    def __init__(self, model: Any = "gpt-tiny",
                 engine_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0):
        from .deployment import build_model

        m, params = build_model(model, seed=seed)
        self.engine = LLMEngine(m, params,
                                EngineConfig(**(engine_config or {})),
                                name="disagg-decode")
        self.engine.start()

    def ingest(self, shipped: Dict[str, Any]) -> Dict[str, Any]:
        stream = self.engine.add_prefilled(
            shipped["prompt"], shipped["kv"], shipped["first_token"],
            max_tokens=shipped["max_tokens"], eos_id=shipped["eos_id"])
        toks = stream.tokens()
        return {"tokens": toks, "finish_reason": stream.finish_reason}

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()


class DisaggLLM:
    """Driver-side convenience: two stage actors + the compiled 2-stage
    pipeline. `generate()` pushes one request through the channel.

    ``codec`` ("int8"/"e4m3", docs/COLLECTIVES.md) block-quantizes the
    prefill→decode KV shipment on the wire — the dominant payload of
    the disagg split drops to ~1/4 of its fp32 bytes; the decode engine
    adopts the dequantized blocks, so decode runs on a KV image with
    per-block quantization error (greedy completions on well-separated
    logits are typically unchanged; the bench row pins the latency/
    bytes trade). None = exact, byte-identical to the pre-codec path.
    """

    def __init__(self, model: Any = "gpt-tiny", block_size: int = 16,
                 engine_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0, codec: Optional[str] = None):
        import ray_tpu
        from ray_tpu.cgraph import InputNode

        eng_cfg = dict(engine_config or {})
        eng_cfg.setdefault("block_size", block_size)
        prefill_cls = ray_tpu.remote(PrefillStage)
        decode_cls = ray_tpu.remote(DecodeStage)
        self._prefill = prefill_cls.remote(model, block_size, seed=seed)
        self._decode = decode_cls.remote(model, eng_cfg, seed=seed)
        with InputNode() as inp:
            dag = self._decode.ingest.bind(self._prefill.prefill.bind(inp))
        self._compiled = dag.experimental_compile(codec=codec)

    def generate(self, tokens: List[int], max_tokens: int = 16,
                 eos_id: Any = "__default__",
                 timeout: float = 120.0) -> Dict[str, Any]:
        return self._compiled.execute(
            {"tokens": tokens, "max_tokens": max_tokens,
             "eos_id": eos_id}).get(timeout=timeout)

    def stats(self, timeout: float = 30.0) -> Dict[str, Any]:
        import ray_tpu

        return ray_tpu.get(self._decode.stats.remote(), timeout=timeout)

    def shutdown(self) -> None:
        import ray_tpu

        try:
            self._compiled.teardown()
        except Exception:
            pass
        for actor in (self._prefill, self._decode):
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
