"""LLMServer — the serve-deployment face of the engine.

One replica hosts one LLMEngine; the serve layer (controller, handles,
proxies) sees an ordinary user callable:

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMServer

    app = serve.deployment(num_replicas=1)(LLMServer).bind(
        model="gpt-tiny", engine_config={"max_batch": 8})
    handle = serve.run(app)
    # full completion
    out = ray_tpu.get(handle.remote({"tokens": [1, 5, 9],
                                     "max_tokens": 16}), timeout=60)
    # token streaming (handle async-iterates too; proxies speak
    # NDJSON or SSE — ?stream=1 / ?stream=sse)
    for tok in handle.options(stream=True).remote(
            {"tokens": [1, 5, 9], "max_tokens": 16, "stream": True}): ...

`queue_len()` reports the engine's waiting+running depth; the replica
ships it in its health ping so the controller's request-based autoscaler
scales on engine backlog, not just in-flight RPCs (controller.py).

Tensor parallelism: ``engine_config={"tp": N}`` makes this replica span
an N-chip mesh — prefill/decode lower sharded (heads/FFN on ``tp``, KV
pool block-sharded per chip; docs/SHARDING.md) while the serve layer
still sees one replica actor. ``stats()`` then carries
``kv_blocks_per_chip`` / ``kv_bytes_per_chip``.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

from .engine import EngineConfig, LLMEngine


def build_model(model: Any = "gpt-tiny", seed: int = 0) -> Tuple[Any, Any]:
    """-> (model, params). `model` is a registry name ("gpt-tiny",
    "llama-tiny", "gpt2-small", "llama2-7b"), or a dict
    {"family": "gpt"|"llama", **config_kwargs} for explicit sizing.
    Params initialize from `seed` so disaggregated stages agree."""
    import jax
    import jax.numpy as jnp

    from ...models import GPT, GPTConfig, Llama, LlamaConfig

    if isinstance(model, str):
        registry = {
            "gpt-tiny": ("gpt", dict(dtype=jnp.float32, use_flash=False)),
            "llama-tiny": ("llama", dict(dtype=jnp.float32,
                                         use_flash=False)),
            "gpt2-small": ("gpt", dict(preset="small")),
            "llama2-7b": ("llama", dict(preset="llama2_7b")),
        }
        if model not in registry:
            raise ValueError(f"unknown model {model!r}; "
                             f"known: {sorted(registry)}")
        family, kw = registry[model]
        preset = kw.pop("preset", "tiny")
    else:
        kw = dict(model)
        family = kw.pop("family")
        preset = kw.pop("preset", "tiny")
    if family == "gpt":
        cfg = getattr(GPTConfig, preset)(**kw)
        m = GPT(cfg)
    elif family == "llama":
        cfg = getattr(LlamaConfig, preset)(**kw)
        m = Llama(cfg)
    else:
        raise ValueError(f"unknown model family {family!r}")
    params = jax.jit(m.init)(jax.random.PRNGKey(seed))
    return m, params


class LLMServer:
    """Serve user callable wrapping an LLMEngine (wrap with
    `serve.deployment(...)(LLMServer)`)."""

    def __init__(self, model: Any = "gpt-tiny",
                 engine_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0, name: str = ""):
        m, params = build_model(model, seed=seed)
        cfg = EngineConfig(**(engine_config or {}))
        self.engine = LLMEngine(m, params, cfg, name=name or "serve")
        self.engine.start()

    # -- request path ---------------------------------------------------------

    def __call__(self, payload: Dict[str, Any]):
        """payload: {"tokens": [ints], "max_tokens": n, "eos_id": id?,
        "stream": bool?}. stream=True returns a generator (route it with
        handle.options(stream=True) / proxy ?stream=...); otherwise the
        full completion dict."""
        if not isinstance(payload, dict) or "tokens" not in payload:
            raise ValueError("payload must be a dict with 'tokens'")
        stream = self.engine.add_request(
            payload["tokens"], int(payload.get("max_tokens", 16)),
            eos_id=payload.get("eos_id", "__default__"))
        if payload.get("stream"):
            return self._stream_tokens(stream)
        t0 = time.perf_counter()
        toks = stream.tokens()
        return {"request_id": stream.request_id, "tokens": toks,
                "finish_reason": stream.finish_reason,
                "gen_s": round(time.perf_counter() - t0, 4)}

    @staticmethod
    def _stream_tokens(stream):
        for tok in stream:
            yield tok

    # -- control plane --------------------------------------------------------

    def queue_len(self) -> int:
        """Engine backlog — shipped in the replica health ping and read
        by the controller's autoscaler (max'd with in-flight RPCs)."""
        return self.engine.queue_depth()

    def cache_stats(self) -> Dict[str, Any]:
        """Prefix-cache health (cache_hit_rate, prefix_blocks_resident,
        ...) — the replica merges this into its health ping so the
        controller and the session-aware router can prefer cache-warm
        replicas (controller.py / handle.py)."""
        return self.engine.cache_stats()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def check_health(self) -> None:
        if not self.engine.is_alive():
            raise RuntimeError("engine scheduler thread is dead")

    def __del__(self):
        try:
            self.engine.stop(timeout=2.0)
        except Exception:
            pass
