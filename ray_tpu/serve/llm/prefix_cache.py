"""Radix prefix cache over the paged KV block pool.

The serving-side answer to real chat traffic (ROADMAP item 3 /
docs/LLM_SERVE.md "Prefix caching & sessions"): shared system-prompt /
few-shot prefixes and multi-turn session contexts dominate production
token streams, and their KV is identical across requests under greedy
decode. This module owns the host-side index that makes those tokens
free: a radix tree over token sequences whose nodes own refcounted
:class:`~.kv_cache.BlockPool` block ranges.

Design points (SGLang's RadixAttention is the published shape,
PAPERS.md):

- **Block-aligned nodes.** Every node covers ``len(blocks) *
  block_size`` tokens — only FULL blocks are cached, so a cached block
  is immutable by construction: decode writes always land in a
  sequence's private tail block, never a shared one. Node edges split
  only at block boundaries; two siblings may share up to
  ``block_size - 1`` leading tokens (they own distinct blocks), so
  children are bucketed by first token and disambiguated by longest
  common prefix.
- **Copy-on-write at the divergence point.** A lookup that diverges
  mid-block still reports the partially-shared block
  (:attr:`PrefixMatch.partial_block` + how many of its tokens match):
  the engine duplicates that block into a fresh allocation before the
  new writer extends it, so ``partial_len`` tokens of prefill are saved
  without ever mutating shared state.
- **Refcounted sharing.** The cache holds ONE pool reference on every
  block it indexes (taken at insert); each sequence reusing a prefix
  holds its own reference (``pool.retain``). Retiring or preempting a
  sequence releases only its references — the cached prefix stays
  resident, which is exactly "preempted sequences release only their
  private tail".
- **LRU eviction under pool pressure.** ``evict(n)`` walks leaves in
  least-recently-matched order and releases nodes whose blocks have no
  holder besides the cache (pool refcount 1) until ``n`` blocks came
  free — blocks still referenced by a running sequence are never
  reclaimed, and interior nodes become evictable as their children go.

The tree never touches jax: it indexes block IDS; the engine owns the
device arrays and the COW copies.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .kv_cache import BlockPool


class _RadixNode:
    __slots__ = ("tokens", "blocks", "children", "parent", "last_used")

    def __init__(self, tokens: List[int], blocks: List[int],
                 parent: Optional["_RadixNode"]):
        self.tokens = tokens           # edge label; len == len(blocks)*Bs
        self.blocks = blocks           # pool block ids, table order
        self.children: Dict[int, List["_RadixNode"]] = {}
        self.parent = parent
        self.last_used = 0             # cache clock at last match/insert


@dataclass
class PrefixMatch:
    """Result of :meth:`PrefixCache.match`.

    ``blocks`` covers ``num_tokens`` tokens of fully-shared full blocks
    (``num_tokens == len(blocks) * block_size``). When the lookup
    diverged mid-block, ``partial_block`` names the cached block whose
    first ``partial_len`` tokens also match — the COW candidate. Total
    reusable tokens = ``num_tokens + partial_len``.
    """
    num_tokens: int = 0
    blocks: List[int] = field(default_factory=list)
    partial_block: Optional[int] = None
    partial_len: int = 0


class PrefixCache:
    """Radix tree mapping token-sequence prefixes to resident KV blocks.

    NOT thread-safe on its own — the engine serializes every call under
    its scheduler lock, the same discipline the BlockPool gets.
    """

    def __init__(self, pool: BlockPool, block_size: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.pool = pool
        self.block_size = block_size
        self._root = _RadixNode([], [], None)
        self._clock = 0                # monotonic LRU stamp
        self._nodes = 0
        self._resident_blocks = 0
        self.evictions = 0

    # -- introspection -------------------------------------------------------

    @property
    def num_nodes(self) -> int:
        return self._nodes

    @property
    def resident_blocks(self) -> int:
        """Blocks the cache currently indexes (and holds one pool
        reference each on) — the ``prefix_blocks_resident`` surface."""
        return self._resident_blocks

    # -- lookup --------------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _lcp(a: List[int], b: List[int], start: int) -> int:
        """Longest common prefix of a[start:] and b."""
        n = min(len(a) - start, len(b))
        i = 0
        while i < n and a[start + i] == b[i]:
            i += 1
        return i

    def _best_child(self, node: _RadixNode, tokens: List[int],
                    pos: int) -> tuple:
        """(child, lcp) with the longest common prefix at tokens[pos:],
        or (None, 0). Siblings sharing a first token are disambiguated
        here — divergence inside the first block keeps them distinct
        nodes rather than splitting below block granularity."""
        if pos >= len(tokens):
            return None, 0
        best, best_l = None, 0
        for child in node.children.get(tokens[pos], ()):
            l = self._lcp(tokens, child.tokens, pos)
            if l > best_l:
                best, best_l = child, l
        return best, best_l

    def match(self, tokens: List[int]) -> PrefixMatch:
        """Longest cached prefix of ``tokens``. Fully-matched FULL
        blocks come back in table order; a mid-block divergence is
        reported as the COW candidate. Touches every matched node's LRU
        stamp. The caller retains ``blocks`` (and copies
        ``partial_block``) before using them."""
        m = PrefixMatch()
        node, pos = self._root, 0
        now = self._tick()
        while True:
            child, l = self._best_child(node, tokens, pos)
            if child is None or l == 0:
                return m
            child.last_used = now
            if l == len(child.tokens):
                # full edge match: every block is reusable as-is
                m.blocks.extend(child.blocks)
                m.num_tokens += len(child.tokens)
                node, pos = child, pos + l
                continue
            # partial edge match: whole blocks first, then the COW block
            fb = l // self.block_size
            m.blocks.extend(child.blocks[:fb])
            m.num_tokens += fb * self.block_size
            rem = l - fb * self.block_size
            if rem:
                m.partial_block = child.blocks[fb]
                m.partial_len = rem
            return m

    # -- insert --------------------------------------------------------------

    def insert(self, tokens: List[int], blocks: List[int]) -> int:
        """Index the full-block prefix of ``tokens`` (held in
        ``blocks``, table order). Only ``len(tokens) // block_size``
        blocks are cached — the partial tail stays the sequence's
        private property. Already-cached spans are skipped (idempotent;
        re-inserting a reused prefix never double-retains). Returns the
        number of NEWLY indexed blocks, each now holding one cache
        reference."""
        bs = self.block_size
        n_full = len(tokens) // bs
        if n_full == 0:
            return 0
        if len(blocks) < n_full:
            raise ValueError(
                f"{len(tokens)} tokens need {n_full} full blocks; got "
                f"{len(blocks)}")
        tokens = [int(t) for t in tokens[:n_full * bs]]
        blocks = list(blocks[:n_full])
        node, pos = self._root, 0
        now = self._tick()
        while pos < len(tokens):
            child, l = self._best_child(node, tokens, pos)
            fb = (l // bs) * bs        # block-aligned shared span
            if child is None or fb == 0:
                # nothing block-aligned in common: new sibling edge with
                # the remaining chain (divergence inside the first block
                # keeps both nodes whole — they own distinct blocks)
                new_tokens = tokens[pos:]
                new_blocks = blocks[pos // bs:]
                self.pool.retain(new_blocks)
                n = _RadixNode(new_tokens, new_blocks, node)
                n.last_used = now
                node.children.setdefault(tokens[pos], []).append(n)
                self._nodes += 1
                self._resident_blocks += len(new_blocks)
                return len(new_blocks)
            child.last_used = now
            if fb < len(child.tokens):
                # shared span ends inside this edge: split it at the
                # block boundary so the tail becomes its own node
                child = self._split(child, fb)
                child.last_used = now
            node, pos = child, pos + fb
        return 0

    def _split(self, node: _RadixNode, at: int) -> _RadixNode:
        """Split an edge at block-aligned token offset ``at`` (> 0,
        < len(node.tokens)): ``node`` keeps the head span, a new child
        takes the tail (tokens, blocks, and grandchildren). Returns the
        head node."""
        bs = self.block_size
        assert 0 < at < len(node.tokens) and at % bs == 0, at
        tail = _RadixNode(node.tokens[at:], node.blocks[at // bs:], node)
        tail.children = node.children
        for bucket in tail.children.values():
            for gc in bucket:
                gc.parent = tail
        tail.last_used = node.last_used
        node.tokens = node.tokens[:at]
        node.blocks = node.blocks[:at // bs]
        node.children = {tail.tokens[0]: [tail]}
        self._nodes += 1
        return node

    # -- eviction ------------------------------------------------------------

    def evict(self, num_blocks: int) -> int:
        """Release least-recently-used leaf nodes until ``num_blocks``
        pool blocks came free or nothing more is evictable. Only nodes
        whose every block has refcount 1 (the cache's own reference) are
        candidates — blocks shared with a running sequence stay. Parents
        whose last child went become leaves and join the heap. One tree
        walk total: O(nodes + victims·log nodes), not a re-scan per
        victim (this runs on the engine's allocation hot path)."""
        import heapq

        heap = [(leaf.last_used, id(leaf), leaf) for leaf in self._leaves()]
        heapq.heapify(heap)
        freed = 0
        while freed < num_blocks and heap:
            _, _, victim = heapq.heappop(heap)
            if victim.children or victim.parent is None:
                continue               # stale entry: re-parented/removed
            if any(self.pool.refcount(b) != 1 for b in victim.blocks):
                continue               # shared with a live sequence
            parent = victim.parent
            freed += len(victim.blocks)
            self._remove(victim)
            self.evictions += 1
            if parent is not self._root and not parent.children:
                heapq.heappush(heap,
                               (parent.last_used, id(parent), parent))
        return freed

    def _leaves(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            had_child = False
            for bucket in n.children.values():
                for c in bucket:
                    had_child = True
                    stack.append(c)
            if not had_child and n is not self._root:
                yield n

    def _remove(self, node: _RadixNode) -> None:
        parent = node.parent
        key = node.tokens[0]
        bucket = parent.children.get(key, [])
        if node in bucket:
            bucket.remove(node)
            if not bucket:
                del parent.children[key]
        self.pool.free(node.blocks)
        self._nodes -= 1
        self._resident_blocks -= len(node.blocks)
        node.parent = None             # marks the node as removed

    def clear(self) -> int:
        """Drop every cached prefix (drain / pool-rescue hook); returns
        blocks whose cache reference was released. Iterative post-order
        (children removed before parents) — a long-context chain is one
        node per block and would blow Python's recursion limit."""
        released = 0
        stack = [(self._root, False)]
        while stack:
            node, expanded = stack.pop()
            if not expanded:
                stack.append((node, True))
                for bucket in node.children.values():
                    for c in bucket:
                        stack.append((c, False))
            elif node is not self._root:
                released += len(node.blocks)
                self._remove(node)
        return released

    # -- invariants ----------------------------------------------------------

    def check_invariants(self) -> None:
        """Structural + shared-block invariants: every node is whole
        blocks, block-count matches token-count, no block indexed twice,
        every indexed block live in the pool, resident accounting
        exact."""
        seen: Dict[int, bool] = {}
        nodes = 0
        stack = [self._root]
        while stack:
            n = stack.pop()
            if n is not self._root:
                nodes += 1
                if not n.tokens:
                    raise AssertionError("empty cache node")
                if len(n.tokens) != len(n.blocks) * self.block_size:
                    raise AssertionError(
                        f"node covers {len(n.tokens)} tokens with "
                        f"{len(n.blocks)} blocks (block_size "
                        f"{self.block_size}) — nodes must be whole blocks")
                for b in n.blocks:
                    if b in seen:
                        raise AssertionError(f"block {b} indexed twice")
                    seen[b] = True
                    if self.pool.refcount(b) < 1:
                        raise AssertionError(
                            f"cached block {b} is free in the pool — "
                            f"the cache reference leaked")
            for key, bucket in n.children.items():
                for c in bucket:
                    if c.parent is not n:
                        raise AssertionError("parent pointer corrupt")
                    if c.tokens[0] != key:
                        raise AssertionError("child filed under wrong key")
                    stack.append(c)
        if nodes != self._nodes:
            raise AssertionError(
                f"node accounting: counted {nodes}, tracked {self._nodes}")
        if len(seen) != self._resident_blocks:
            raise AssertionError(
                f"resident accounting: counted {len(seen)}, tracked "
                f"{self._resident_blocks}")
