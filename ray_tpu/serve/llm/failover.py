"""Zero-loss LLM replica failover — the token-stream resume policy.

The routing handle owns the generic machinery (serve/handle.py
``FailoverResponseGenerator``): it tracks which replica a stream is
assigned to and, on replica death, asks a ``resume`` callable for the
continuation request. This module supplies the LLM semantics of that
continuation: **already-streamed tokens become the forced prefix** of a
re-prefill on a surviving replica.

Why that is exact: every replica of one LLM deployment builds the same
model from the same seed (deployment.build_model), and the engine
decodes greedily — so prefilling ``prompt + streamed_tokens`` on any
replica emits precisely the token the dead replica would have produced
next (the same argument that makes engine-level preemption token-exact,
serve/llm/engine.py _preempt). The client sees a stall while the new
replica prefills, never an error, a duplicated token, or a corrupted
stream.

    handle = serve.run(app)
    stream = resilient_stream(handle, {"tokens": [...],
                                       "max_tokens": 64})
    for tok in stream: ...       # survives replica kills mid-stream

Bounds: a continuation's prompt is the original prompt plus everything
already streamed, so it must still fit the engine's largest prefill
bucket — the same ceiling engine preemption lives under. Streams whose
context outgrows the bucket fail loudly on the resumed replica rather
than silently truncating.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple


def llm_resume(args: tuple, kwargs: dict,
               yielded: list) -> Optional[Tuple[tuple, dict]]:
    """Build the continuation request after a replica death: streamed
    tokens are appended to the prompt (forced prefix) and deducted from
    the generation budget. None = the stream was already complete."""
    payload: Dict[str, Any] = dict(args[0])
    remaining = int(payload.get("max_tokens", 16)) - len(yielded)
    if remaining <= 0:
        return None  # death landed between the final token and EOS mark
    payload["tokens"] = (list(payload["tokens"])
                         + [int(t) for t in yielded])
    payload["max_tokens"] = remaining
    return (payload,) + tuple(args[1:]), kwargs


def resilient_stream(handle, payload: Dict[str, Any], *,
                     multiplexed_model_id: str = "",
                     session_id: str = ""):
    """Stream tokens from an LLMServer deployment with replica-failover:
    returns a generator (sync and async iterable) whose token sequence
    is complete and prefix-consistent even when replicas die mid-stream.

    ``payload`` is the LLMServer request dict ({"tokens", "max_tokens",
    "eos_id"?}); "stream" is forced on.

    Caveat: an ``eos_id`` request that dies after the EOS token was
    generated but before the stream closed resumes with the EOS inside
    the forced prefix — the continuation then runs to its (reduced)
    max_tokens. Consumers that stop at EOS themselves (the standard
    client shape) are unaffected.
    """
    payload = {**payload, "stream": True}
    return handle._submit_streaming(
        "__call__", (payload,), {}, mux_id=multiplexed_model_id,
        resume=llm_resume, session_id=session_id)
