"""Block pool + block tables — the host-side half of the paged KV cache.

The device arrays live in the engine (``model.init_paged_cache``); this
module owns the *accounting*: which pool blocks are free, which sequence
holds which blocks, and the alloc/free discipline whose failure path is
preemption-and-requeue (engine.py). Kept separate so leak/accounting
invariants are testable without touching jax at all.
"""
from __future__ import annotations

from typing import List, Optional


class BlockPool:
    """Fixed pool of KV blocks. alloc() is all-or-nothing: a partial
    grant would deadlock two growing sequences against each other."""

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be positive, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._used = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self._used

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None when the pool can't satisfy the request
        (caller preempts or waits). n == 0 returns []."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._used += n
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"free of unknown block {b}")
        if self._used < len(blocks):
            raise ValueError("double free: more blocks returned than held")
        self._used -= len(blocks)
        self._free.extend(blocks)

    def check_leaks(self) -> None:
        """Invariant: every block is either free or accounted used."""
        if len(self._free) + self._used != self.num_blocks:
            raise AssertionError(
                f"block leak: {len(self._free)} free + {self._used} used "
                f"!= {self.num_blocks}")
        if len(set(self._free)) != len(self._free):
            raise AssertionError("duplicate block in free list")


def blocks_for_tokens(num_tokens: int, block_size: int) -> int:
    """Blocks needed to hold positions [0, num_tokens)."""
    if num_tokens <= 0:
        return 0
    return (num_tokens - 1) // block_size + 1
