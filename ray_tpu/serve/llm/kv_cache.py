"""Block pool + block tables — the host-side half of the paged KV cache.

The device arrays live in the engine (``model.init_paged_cache``); this
module owns the *accounting*: which pool blocks are free, which sequence
holds which blocks, and the alloc/free discipline whose failure path is
preemption-and-requeue (engine.py). Kept separate so leak/accounting
invariants are testable without touching jax at all.

With ``shards > 1`` (tensor-parallel engines, docs/SHARDING.md) the pool
mirrors the device layout of the block-sharded cache arrays: block ids
``[c*N/shards, (c+1)*N/shards)`` live on chip ``c``, and allocation
balances across chips (most-free-first) so per-chip KV memory stays
even. ``used_per_shard()`` backs the per-chip occupancy gauge
``ray_tpu_llm_kv_blocks_used{chip=}``.
"""
from __future__ import annotations

from typing import List, Optional


class BlockPool:
    """Fixed pool of KV blocks. alloc() is all-or-nothing: a partial
    grant would deadlock two growing sequences against each other."""

    def __init__(self, num_blocks: int, shards: int = 1):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be positive, got {num_blocks}")
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if num_blocks % shards:
            raise ValueError(
                f"num_blocks {num_blocks} not divisible into {shards} "
                f"shards — the pool must tile the block-sharded cache "
                f"exactly (raise num_blocks to a multiple of tp)")
        self.num_blocks = num_blocks
        self.shards = shards
        per = num_blocks // shards
        self._per_shard = per
        # per-shard LIFO free lists (ascending ids pop first)
        self._free_by_shard: List[List[int]] = [
            list(range((s + 1) * per - 1, s * per - 1, -1))
            for s in range(shards)]
        self._used = 0

    @property
    def free_count(self) -> int:
        return sum(len(f) for f in self._free_by_shard)

    @property
    def used_count(self) -> int:
        return self._used

    def shard_of(self, block: int) -> int:
        """Which chip's cache slice holds this block id."""
        return block // self._per_shard

    def used_per_shard(self) -> List[int]:
        """Allocated blocks per chip (the {chip=} gauge series)."""
        return [self._per_shard - len(f) for f in self._free_by_shard]

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None when the pool can't satisfy the request
        (caller preempts or waits). n == 0 returns []. Blocks come from
        the fullest-free shard first, so tp chips fill evenly."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.free_count:
            return None
        out: List[int] = []
        for _ in range(n):
            # most-free shard (lowest index on ties): O(shards) per
            # block with shards <= tp <= 8 — not a hot path
            s = max(range(self.shards),
                    key=lambda i: (len(self._free_by_shard[i]), -i))
            out.append(self._free_by_shard[s].pop())
        self._used += n
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"free of unknown block {b}")
        if self._used < len(blocks):
            raise ValueError("double free: more blocks returned than held")
        self._used -= len(blocks)
        for b in blocks:
            self._free_by_shard[self.shard_of(b)].append(b)

    def check_leaks(self) -> None:
        """Invariant: every block is either free or accounted used."""
        free = [b for f in self._free_by_shard for b in f]
        if len(free) + self._used != self.num_blocks:
            raise AssertionError(
                f"block leak: {len(free)} free + {self._used} used "
                f"!= {self.num_blocks}")
        if len(set(free)) != len(free):
            raise AssertionError("duplicate block in free list")
        for s, f in enumerate(self._free_by_shard):
            for b in f:
                if self.shard_of(b) != s:
                    raise AssertionError(
                        f"block {b} filed under shard {s}, belongs to "
                        f"{self.shard_of(b)}")


def blocks_for_tokens(num_tokens: int, block_size: int) -> int:
    """Blocks needed to hold positions [0, num_tokens)."""
    if num_tokens <= 0:
        return 0
    return (num_tokens - 1) // block_size + 1
