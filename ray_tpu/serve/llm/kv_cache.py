"""Block pool + block tables — the host-side half of the paged KV cache.

The device arrays live in the engine (``model.init_paged_cache``); this
module owns the *accounting*: which pool blocks are free, which sequence
holds which blocks, and the alloc/free discipline whose failure path is
preemption-and-requeue (engine.py). Kept separate so leak/accounting
invariants are testable without touching jax at all.

Blocks are REFCOUNTED (prefix caching, docs/LLM_SERVE.md "Prefix
caching & sessions"): the radix prefix cache and every sequence reusing
a cached prefix hold one reference each on the shared blocks.
``alloc`` grants fresh blocks at refcount 1, ``retain`` adds a
reference, ``free`` drops one — a block returns to the free list only
when its last reference is released. ``used_count`` counts every live
block ONCE regardless of how many holders share it, so the
``ray_tpu_llm_kv_blocks_used`` gauge can never report occupancy above
pool capacity, and ``check_leaks`` verifies the shared-block invariant
(free list and live refcounts partition the pool exactly).

With ``shards > 1`` (tensor-parallel engines, docs/SHARDING.md) the pool
mirrors the device layout of the block-sharded cache arrays: block ids
``[c*N/shards, (c+1)*N/shards)`` live on chip ``c``, and allocation
balances across chips (most-free-first) so per-chip KV memory stays
even. ``used_per_shard()`` backs the per-chip occupancy gauge
``ray_tpu_llm_kv_blocks_used{chip=}``.
"""
from __future__ import annotations

from typing import List, Optional


class BlockPool:
    """Fixed pool of refcounted KV blocks. alloc() is all-or-nothing: a
    partial grant would deadlock two growing sequences against each
    other."""

    def __init__(self, num_blocks: int, shards: int = 1):
        if num_blocks <= 0:
            raise ValueError(f"num_blocks must be positive, got {num_blocks}")
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if num_blocks % shards:
            raise ValueError(
                f"num_blocks {num_blocks} not divisible into {shards} "
                f"shards — the pool must tile the block-sharded cache "
                f"exactly (raise num_blocks to a multiple of tp)")
        self.num_blocks = num_blocks
        self.shards = shards
        per = num_blocks // shards
        self._per_shard = per
        # per-shard LIFO free lists (ascending ids pop first)
        self._free_by_shard: List[List[int]] = [
            list(range((s + 1) * per - 1, s * per - 1, -1))
            for s in range(shards)]
        self._refcnt: List[int] = [0] * num_blocks
        self._used = 0                 # live blocks, each counted ONCE

    @property
    def free_count(self) -> int:
        return sum(len(f) for f in self._free_by_shard)

    @property
    def used_count(self) -> int:
        """Live blocks, shared blocks counted once — the occupancy the
        ``ray_tpu_llm_kv_blocks_used`` gauge reports. Never exceeds
        ``num_blocks`` no matter how many holders share a block."""
        return self._used

    def refcount(self, block: int) -> int:
        """Current reference count of a block (0 = free)."""
        if not 0 <= block < self.num_blocks:
            raise ValueError(f"unknown block {block}")
        return self._refcnt[block]

    def shard_of(self, block: int) -> int:
        """Which chip's cache slice holds this block id."""
        return block // self._per_shard

    def used_per_shard(self) -> List[int]:
        """Live blocks per chip (the {chip=} gauge series) — shared
        blocks counted once, same as used_count."""
        return [self._per_shard - len(f) for f in self._free_by_shard]

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh blocks at refcount 1, or None when the pool can't
        satisfy the request (caller evicts cached prefixes, preempts, or
        waits). n == 0 returns []. Blocks come from the fullest-free
        shard first, so tp chips fill evenly."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.free_count:
            return None
        out: List[int] = []
        for _ in range(n):
            # most-free shard (lowest index on ties): O(shards) per
            # block with shards <= tp <= 8 — not a hot path
            s = max(range(self.shards),
                    key=lambda i: (len(self._free_by_shard[i]), -i))
            b = self._free_by_shard[s].pop()
            self._refcnt[b] = 1
            out.append(b)
        self._used += n
        return out

    def retain(self, blocks: List[int]) -> None:
        """Add one reference to each (live) block — how a sequence
        reusing a cached prefix, or the prefix cache itself, shares
        blocks another holder allocated."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"retain of unknown block {b}")
            if self._refcnt[b] <= 0:
                raise ValueError(f"retain of free block {b}")
        for b in blocks:
            self._refcnt[b] += 1

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block; a block returns to the free
        list when its last holder releases it."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"free of unknown block {b}")
        # validate the whole batch before mutating: a double free must
        # not release the valid half of the list first
        counts = {}
        for b in blocks:
            counts[b] = counts.get(b, 0) + 1
        for b, n in counts.items():
            if self._refcnt[b] < n:
                raise ValueError(
                    f"double free: block {b} released {n}x with only "
                    f"{self._refcnt[b]} reference(s) held")
        for b in blocks:
            self._refcnt[b] -= 1
            if self._refcnt[b] == 0:
                self._used -= 1
                self._free_by_shard[self.shard_of(b)].append(b)

    def check_leaks(self) -> None:
        """Invariants: the free list and the live refcounts partition
        the pool exactly — every block is either free (refcount 0) or
        live (refcount >= 1) and counted once in used_count; no block
        appears twice in a free list; shard filing is consistent."""
        free = [b for f in self._free_by_shard for b in f]
        if len(free) + self._used != self.num_blocks:
            raise AssertionError(
                f"block leak: {len(free)} free + {self._used} used "
                f"!= {self.num_blocks}")
        if len(set(free)) != len(free):
            raise AssertionError("duplicate block in free list")
        free_set = set(free)
        for b in range(self.num_blocks):
            rc = self._refcnt[b]
            if rc < 0:
                raise AssertionError(f"block {b} refcount {rc} < 0")
            if rc == 0 and b not in free_set:
                raise AssertionError(
                    f"block {b} has refcount 0 but is not on the free "
                    f"list (leaked)")
            if rc > 0 and b in free_set:
                raise AssertionError(
                    f"block {b} is free AND holds {rc} reference(s) — "
                    f"a sequence or the prefix cache would read blocks "
                    f"the allocator can hand out again")
        for s, f in enumerate(self._free_by_shard):
            for b in f:
                if self.shard_of(b) != s:
                    raise AssertionError(
                        f"block {b} filed under shard {s}, belongs to "
                        f"{self.shard_of(b)}")


def blocks_for_tokens(num_tokens: int, block_size: int) -> int:
    """Blocks needed to hold positions [0, num_tokens)."""
    if num_tokens <= 0:
        return 0
    return (num_tokens - 1) // block_size + 1
