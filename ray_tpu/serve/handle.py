"""DeploymentHandle — the routing client.

Equivalent of the reference's handle + router (ref:
python/ray/serve/handle.py DeploymentHandle/DeploymentResponse;
_private/router.py:263 PowerOfTwoChoicesReplicaScheduler, choose_two
:411). remote() returns a DeploymentResponse backed by a router worker
that owns the request until a replica finishes it: power-of-two-choices
over handle-local in-flight counts, backoff when every replica is at
max_concurrent_queries, and transparent re-routing when a replica dies
mid-request (the reference router reassigns exactly the same way).
"""
from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                ObjectLostError, WorkerCrashedError)
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing
from ray_tpu.util.retry import RetryPolicy

from .controller import CONTROLLER_NAME

# errors that mean "the replica (or its worker) is gone" — the router
# still owns the request and may reassign it; anything else is the
# application's error and propagates
REPLICA_LOST_ERRORS = (ActorDiedError, ActorUnavailableError,
                       WorkerCrashedError, ObjectLostError)

# end-to-end request latency as the router sees it: replica pick +
# queueing + execution + result fetch (ref: the reference's
# serve_deployment_processing_latency_ms family)
_H_SERVE_REQUEST = _metrics.Histogram(
    "ray_tpu_serve_request_seconds",
    "end-to-end serve request latency through the routing handle",
    tag_keys=("deployment",))

# a session whose affinity replica vanished (death, drain, saturation)
# was re-pinned to a different replica — its cached prefix KV must be
# rebuilt there, so this counts cache-warmth lost to replica churn
_C_SESSION_REROUTES = _metrics.Counter(
    "ray_tpu_serve_session_reroutes_total",
    "session-affinity reassignments to a different replica",
    tag_keys=("deployment",))

# per-deployment SLO accounting (DeploymentConfig.slo_target_s): every
# routed request falls into exactly one of these two, so
# violated / (ok + violated) is the SLO miss rate `ray_tpu top` shows
_C_SLO_OK = _metrics.Counter(
    "ray_tpu_serve_slo_ok_total",
    "requests that finished within the deployment's latency SLO",
    tag_keys=("deployment",))
_C_SLO_VIOLATED = _metrics.Counter(
    "ray_tpu_serve_slo_violated_total",
    "requests that exceeded the deployment's latency SLO (errors and "
    "routing timeouts included)",
    tag_keys=("deployment",))


class DeploymentResponse:
    """Future-like result of handle.remote(). `ray_tpu.get` accepts it
    (via the __rtpu_result__ protocol), or call .result(timeout)."""

    def __init__(self, fut: Future):
        self._fut = fut

    def result(self, timeout: Optional[float] = None):
        return self._fut.result(timeout)

    def __rtpu_result__(self, timeout: Optional[float] = None):
        return self._fut.result(timeout)

    def done(self) -> bool:
        return self._fut.done()


def extract_session(query: Dict[str, list], data) -> str:
    """Session id for proxy routing: the ``?session=`` query param wins
    over a payload-level ``"session_id"``. ONE precedence rule shared by
    both HTTP proxies — they must never route the same request to
    different sessions."""
    sess = (query.get("session") or [""])[0]
    if not sess and isinstance(data, dict):
        sess = str(data.get("session_id") or "")
    return sess


# query keys the proxies consume themselves — never forwarded as
# payload fields on GET requests
PROXY_CONTROL_PARAMS = ("stream", "model_id", "session")


async def executor_anext(next_fn):
    """One async pull of a blocking `.next()`-style iterator: the call
    hops to the default executor so the caller's event loop stays free
    — the shape async serve deployments and the LLM token streams need
    (serve.llm's TokenStream shares this). Raises StopAsyncIteration
    when the iterator is exhausted."""
    import asyncio

    def pull():
        try:
            return (False, next_fn())
        except StopIteration:
            return (True, None)

    done, item = await asyncio.get_running_loop().run_in_executor(
        None, pull)
    if done:
        raise StopAsyncIteration
    return item


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment response (ref: handle.py
    DeploymentResponseGenerator). Wraps the core ObjectRefGenerator:
    chunks arrive with backpressure; dropping the iterator cancels the
    producer through the streaming-returns protocol."""

    def __init__(self, ref_gen):
        self._gen = ref_gen

    def __iter__(self):
        for ref in self._gen:
            yield ray_tpu.get(ref)

    def __next__(self):
        return ray_tpu.get(next(self._gen))

    def next(self, timeout=None):
        """`__next__` with a per-item deadline (GetTimeoutError on
        expiry) so proxy threads can't be pinned by a hung replica. One
        deadline spans both waits (ref arrival AND payload fetch) — two
        full timeouts would double the documented cap."""
        if timeout is None:
            return ray_tpu.get(next(self._gen))
        deadline = time.monotonic() + timeout
        ref = self._gen.next(timeout=timeout)
        return ray_tpu.get(ref, timeout=max(0.0,
                                            deadline - time.monotonic()))

    def __aiter__(self):
        return self

    async def __anext__(self):
        """Async iteration (`async for chunk in handle.options(
        stream=True).remote(...)`)."""
        return await executor_anext(lambda: self.next(timeout=600.0))


class FailoverResponseGenerator:
    """A streaming response that survives replica death (the LLM serving
    failover surface — docs/FAULT_TOLERANCE.md).

    The handle routes the stream to one replica and records the
    request→replica assignment. When a pull raises a replica-loss error
    (REPLICA_LOST_ERRORS), the generator drops the corpse from the
    routing table, asks ``resume(args, kwargs, yielded_items)`` for the
    continuation request — for LLM streams: already-streamed tokens
    become the forced prefix of a re-prefill — and re-routes it to a
    surviving replica. Items are only recorded AFTER they are handed to
    the consumer, so a mid-flight death can neither lose nor duplicate
    an item: everything the consumer saw is in the forced prefix, and
    everything it didn't see is regenerated.

    ``resume`` returning None means the stream was already complete
    (every item was delivered before the death) — the generator ends
    cleanly instead of re-submitting an empty continuation.
    """

    _MAX_FAILOVERS = 8

    def __init__(self, handle: "DeploymentHandle", method: str, args,
                 kwargs, mux_id: str, resume, deadline: float,
                 session_id: str = "", trace_ctx=None):
        self._handle = handle
        self._method = method
        self._args = args
        self._kwargs = kwargs
        self._mux_id = mux_id
        self._resume = resume
        self._deadline = deadline
        self._session_id = session_id
        self._trace_ctx = trace_ctx
        self._hop_started = 0.0
        self._gen: Optional[DeploymentResponseGenerator] = None
        self._replica = None
        self._yielded: list = []
        self.failovers = 0
        self._finished = False
        self._key = id(self)

    @property
    def replica_actor_id(self):
        r = self._replica
        return None if r is None else r._actor_id

    def _ensure_stream(self) -> None:
        if self._gen is not None:
            return
        self._hop_started = time.time()
        self._gen, self._replica = self._handle._start_stream(
            self._method, self._args, self._kwargs, self._mux_id,
            self._deadline, self._session_id,
            trace_ctx=self._trace_ctx)
        self._handle._assign_stream(self._key, self._replica._actor_id)

    def _finish(self) -> None:
        self._finished = True
        self._handle._unassign_stream(self._key)

    def next(self, timeout=None):
        if self._finished:
            raise StopIteration
        while True:
            self._ensure_stream()
            try:
                item = self._gen.next(timeout=timeout)
            except StopIteration:
                self._finish()
                raise
            except REPLICA_LOST_ERRORS as e:
                self._handle._drop(self._replica)
                self._handle._unassign_stream(self._key)
                self._gen = None
                self._replica = None
                self.failovers += 1
                if self._trace_ctx is not None:
                    # the failed hop lands as a child span on the SAME
                    # trace (the trace store always tail-keeps failover
                    # traces); the resumed hop's spans follow under the
                    # same trace id via the re-routed TRACE_KWARG
                    try:
                        _tracing.record_span(
                            "serve.failover", self._trace_ctx,
                            self._hop_started,
                            deployment=self._handle._name,
                            hop=self.failovers,
                            yielded=len(self._yielded),
                            error=type(e).__name__)
                    except Exception:
                        pass
                try:
                    from ray_tpu.perf.recorder import get_recorder

                    get_recorder().record(
                        "serve.failover", self._handle._name,
                        {"failovers": self.failovers,
                         "yielded": len(self._yielded),
                         "error": type(e).__name__})
                except Exception:
                    pass
                if self.failovers > self._MAX_FAILOVERS:
                    try:
                        from ray_tpu.perf.postmortem import dump_bundle

                        dump_bundle(
                            f"serve failover exhausted: {e!r}",
                            origin=f"serve:{self._handle._name}",
                            meta={"deployment": self._handle._name,
                                  "failovers": self.failovers})
                    except Exception:
                        pass
                    self._finish()
                    raise
                cont = self._resume(self._args, self._kwargs,
                                    list(self._yielded))
                if cont is None:
                    # every item was already delivered: the death hit
                    # between the last item and the end-of-stream marker
                    self._finish()
                    raise StopIteration from None
                self._args, self._kwargs = cont
                # the continuation args now BAKE IN everything yielded so
                # far (forced prefix); reset the ledger to the new
                # baseline — a second death must only replay items
                # yielded since this resume, or the prefix double-counts
                self._yielded = []
                continue
            self._yielded.append(item)
            return item

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def __aiter__(self):
        return self

    async def __anext__(self):
        return await executor_anext(lambda: self.next(timeout=600.0))

    def __del__(self):
        try:
            self._handle._unassign_stream(self._key)
        except Exception:
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str):
        self._name = deployment_name
        self._init_local()

    def options(self, *, stream: bool = False,
                multiplexed_model_id: str = "",
                session_id: str = "") -> "_OptionsHandle":
        """ref: handle.py DeploymentHandle.options(stream=...,
        multiplexed_model_id=...). ``session_id`` pins every request of
        a multi-turn conversation to one replica (the one already
        holding its prefix KV) until that replica dies, drains, or
        saturates — then the session re-routes (counted in
        ray_tpu_serve_session_reroutes_total)."""
        return _OptionsHandle(self, stream, multiplexed_model_id,
                              session_id)

    _MAX_SESSIONS = 4096  # affinity-table LRU cap

    def _init_local(self) -> None:
        import collections

        self._controller = None
        self._version = -1
        self._replicas: list = []
        self._max_q = 8
        self._refreshed = 0.0
        self._inflight: Dict[Any, int] = {}  # replica actor_id -> count
        self._depth_cache: Dict[Any, tuple] = {}  # actor_id -> (ts, depth)
        # session-aware routing (docs/LLM_SERVE.md "Prefix caching &
        # sessions"): session_id -> replica actor_id, LRU-capped.
        # Multi-turn conversations land on the replica already holding
        # their prefix KV; a vanished replica (death/drain) breaks the
        # pin and the next turn re-routes (counted).
        self._sessions: "collections.OrderedDict" = collections.OrderedDict()
        # actor_id hex -> resident prefix blocks (refreshed with the
        # replica list; the p2c tie-break reads it without blocking)
        self._warmth: Dict[str, float] = {}
        self._slo_target: Optional[float] = None
        self._slo_version = -2          # config version the target is for
        self._lock = threading.Lock()
        self._router: Optional[ThreadPoolExecutor] = None

    # handles travel into other deployments' constructors
    def __reduce__(self):
        return (DeploymentHandle, (self._name,))

    # -- replica discovery ----------------------------------------------------

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and self._replicas and now - self._refreshed < 2.0:
                return
        if self._controller is None:
            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        # warmth (resident prefix blocks per replica) piggybacks on the
        # SAME round trip — the _pick tie-break only ever reads the
        # cached map, never blocks on the controller
        version, max_q, replicas, warmth = ray_tpu.get(
            self._controller.get_replicas.remote(self._name, True),
            timeout=30)
        with self._lock:
            self._refreshed = time.monotonic()
            self._warmth = warmth or {}
            if replicas:
                self._replicas = replicas
                self._max_q = max_q or 8
                self._version = version
                live = {r._actor_id for r in replicas}
                self._inflight = {a: c for a, c in self._inflight.items()
                                  if a in live}
            fetch_slo = self._slo_version != version
        if fetch_slo:
            try:
                target = ray_tpu.get(
                    self._controller.get_slo.remote(self._name), timeout=5)
            except Exception:
                # flaky probe: keep the last-known target, retry next
                # version-changed refresh
                target = self._slo_target
            with self._lock:
                self._slo_target = target
                self._slo_version = version

    def _drop(self, replica) -> None:
        with self._lock:
            self._replicas = [r for r in self._replicas if r is not replica]
            self._inflight.pop(replica._actor_id, None)

    # -- power-of-two-choices -------------------------------------------------

    _PROBE_TTL = 0.05  # seconds a probed depth stays fresh

    def _probe_depths(self, replicas) -> list:
        """REPLICA-REPORTED queue depths (ref: router.py:411 choose_two —
        the reference probes candidates rather than trusting router-local
        counts, which are wrong by construction once several handles or
        proxies route to the same replicas). Probes run through the
        replicas' control lane CONCURRENTLY, with a short-TTL cache so
        request bursts don't pay a round trip each; probe failure falls
        back to the handle-local in-flight count."""
        now = time.monotonic()
        out: list = [None] * len(replicas)
        pending = []
        with self._lock:
            for i, r in enumerate(replicas):
                hit = self._depth_cache.get(r._actor_id)
                if hit is not None and now - hit[0] < self._PROBE_TTL:
                    out[i] = hit[1]
                else:
                    pending.append(i)
        refs = []
        for i in pending:
            try:
                refs.append((i, replicas[i].queue_len.options(
                    concurrency_group="control").remote()))
            except Exception:
                refs.append((i, None))
        for i, ref in refs:
            depth = None
            if ref is not None:
                try:
                    depth = int(ray_tpu.get(ref, timeout=1.0))
                except Exception:
                    depth = None
            with self._lock:
                if depth is None:
                    depth = self._inflight.get(replicas[i]._actor_id, 0)
                else:
                    self._depth_cache[replicas[i]._actor_id] = (
                        time.monotonic(), depth)
            out[i] = depth
        return out

    _MUX_TTL = 1.0  # seconds the resident-model map stays fresh

    def _mux_candidates(self, mux_id: str) -> list:
        """Replicas already hosting mux_id (ref: router.py
        multiplexed_model_ids-aware ranking). The resident-model map is
        probed through the control lane with its own TTL cache."""
        now = time.monotonic()
        with self._lock:
            replicas = list(self._replicas)
            cache = getattr(self, "_mux_cache", None)
            if cache is None:
                cache = self._mux_cache = {}
        # fan the probes out BEFORE collecting: R sequential 1s-timeout
        # gets would stall routing by up to R seconds on hung replicas
        stale = []
        for r in replicas:
            hit = cache.get(r._actor_id)
            if hit is None or now - hit[0] >= self._MUX_TTL:
                try:
                    ref = r.multiplexed_model_ids.options(
                        concurrency_group="control").remote()
                except Exception:
                    ref = None
                stale.append((r, ref))
        # one SHARED deadline for the collection: per-ref 1 s timeouts
        # would serialize into an R-second stall when replicas hang
        probe_deadline = time.monotonic() + 1.0
        for r, ref in stale:
            ids = []
            if ref is not None:
                try:
                    ids = ray_tpu.get(ref, timeout=max(
                        0.05, probe_deadline - time.monotonic()))
                except Exception:
                    ids = []
            with self._lock:
                cache[r._actor_id] = (time.monotonic(), set(ids))
        hosts = []
        with self._lock:
            for r in replicas:
                hit = cache.get(r._actor_id)
                if hit is not None and mux_id in hit[1]:
                    hosts.append(r)
        return hosts

    def _pin_session(self, session_id: str, replica) -> None:
        """Record/refresh the session -> replica pin; a pin that moved
        to a DIFFERENT replica counts as a reroute (the session's cached
        prefix must be rebuilt there)."""
        with self._lock:
            old = self._sessions.pop(session_id, None)
            self._sessions[session_id] = replica._actor_id
            while len(self._sessions) > self._MAX_SESSIONS:
                self._sessions.popitem(last=False)
        if old is not None and old != replica._actor_id:
            _C_SESSION_REROUTES.inc(tags={"deployment": self._name})

    def _pick(self, mux_id: str = "", session_id: str = ""):
        """-> replica handle, or None when all replicas are saturated or
        unknown (caller backs off / refreshes)."""
        if session_id:
            with self._lock:
                aid = self._sessions.get(session_id)
                pinned = next((r for r in self._replicas
                               if r._actor_id == aid), None) \
                    if aid is not None else None
            if pinned is not None:
                depth = self._probe_depths([pinned])[0]
                with self._lock:
                    local = self._inflight.get(pinned._actor_id, 0)
                    if max(depth, local) < self._max_q:
                        self._inflight[pinned._actor_id] = local + 1
                        if session_id in self._sessions:
                            self._sessions.move_to_end(session_id)
                        return pinned
            # pin broken (replica dead/draining/saturated): fall through
            # to p2c; _pin_session below records the reroute
        if mux_id:
            hosts = self._mux_candidates(mux_id)
            if hosts:
                depths = self._probe_depths(hosts)
                j = min(range(len(hosts)), key=lambda i: depths[i])
                with self._lock:  # admission check + increment: atomic,
                    # and _max_q may move under a router refresh
                    admit = depths[j] < self._max_q
                    if admit:
                        aid = hosts[j]._actor_id
                        self._inflight[aid] = self._inflight.get(aid, 0) + 1
                if admit:
                    if session_id:
                        self._pin_session(session_id, hosts[j])
                    return hosts[j]
            # no replica hosts the model (or all saturated): fall through
            # to plain p2c — the chosen replica will load it
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                return None
            if n == 1:
                cands = [self._replicas[0]]
            else:
                a, b = random.sample(range(n), 2)
                cands = [self._replicas[a], self._replicas[b]]
        depths = self._probe_depths(cands)
        if len(cands) > 1 and depths[0] == depths[1]:
            # equal load: prefer the cache-warm replica — its resident
            # prefixes make the marginal request cheaper (ROADMAP 3's
            # "balancer prefers cache-warm replicas"). The warmth map is
            # refreshed on the _refresh cadence; this reads the cached
            # copy and never blocks.
            with self._lock:
                warmth = self._warmth
            j = max(range(len(cands)), key=lambda i: warmth.get(
                cands[i]._actor_id.hex(), 0.0))
        else:
            j = min(range(len(cands)), key=lambda i: depths[i])
        cand, depth = cands[j], depths[j]
        with self._lock:
            local = self._inflight.get(cand._actor_id, 0)
            if max(depth, local) >= self._max_q:
                return None
            aid = cand._actor_id
            self._inflight[aid] = local + 1
        if session_id:
            self._pin_session(session_id, cand)
        return cand

    # -- the router worker ----------------------------------------------------

    def _route_blocking(self, method: str, args, kwargs, deadline: float,
                        mux_id: str = "", session_id: str = "",
                        trace_ctx=None):
        import ray_tpu.core.runtime as runtime_mod

        if mux_id:
            from .multiplex import MUX_KWARG

            kwargs = {**kwargs, MUX_KWARG: mux_id}
        route_sid = None
        if trace_ctx is not None:
            # the route span's id crosses into the replica as a reserved
            # kwarg (the MUX_KWARG pattern): replica and engine spans
            # parent under it, stitching one trace across processes
            route_sid = _tracing.new_span_id()
            kwargs = {**kwargs,
                      _tracing.TRACE_KWARG: (trace_ctx[0], route_sid)}
        rt = runtime_mod.get_runtime()
        t_start = time.perf_counter()
        t_wall = time.time()
        ok = False
        err = ""
        try:
            out = self._route_with_retries(rt, method, args, kwargs,
                                           deadline, mux_id, session_id)
            ok = True
            return out
        except BaseException as e:  # noqa: BLE001 — re-raised
            err = type(e).__name__
            raise
        finally:
            dt = time.perf_counter() - t_start
            slo = self._slo_target
            _H_SERVE_REQUEST.observe(
                dt, tags={"deployment": self._name},
                exemplar=trace_ctx[0] if trace_ctx else None)
            if trace_ctx is not None:
                _tracing.record_span(
                    "serve.route", trace_ctx, t_wall,
                    span_id=route_sid, deployment=self._name,
                    session=session_id, error=err,
                    **({"slo_target": slo} if slo is not None else {}))
            if slo is not None:
                # an errored request never met its SLO, whatever the clock
                # says
                if ok and dt <= slo:
                    _C_SLO_OK.inc(tags={"deployment": self._name})
                else:
                    _C_SLO_VIOLATED.inc(tags={"deployment": self._name})

    # shared routing backoff (util/retry.py): saturated/empty replica
    # sets back off exponentially with full jitter so concurrent routers
    # decorrelate; the per-request deadline bounds the whole wait
    _ROUTE_BACKOFF = RetryPolicy(initial_backoff_s=0.0075, multiplier=2.0,
                                 max_backoff_s=0.375, jitter=0.34)

    def _route_with_retries(self, rt, method, args, kwargs, deadline,
                            mux_id, session_id=""):
        saturated = 0
        while True:
            self._refresh()
            replica = self._pick(mux_id, session_id)
            if replica is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{self._name}: no replica accepted the request "
                        f"(all dead or saturated)")
                time.sleep(self._ROUTE_BACKOFF.backoff(saturated))
                saturated += 1
                self._refresh(force=True)
                continue
            aid = replica._actor_id
            try:
                if rt.actor_state(aid) in ("DEAD", "RESTARTING"):
                    raise ActorDiedError("replica not alive")
                ref = replica.handle_request.remote(method, args, kwargs)
                remaining = max(0.1, deadline - time.monotonic())
                return ray_tpu.get(ref, timeout=remaining)
            except REPLICA_LOST_ERRORS:
                # replica died before/while running the request: the router
                # still owns it — drop the corpse and reassign (ref:
                # router.py replica-death reassignment)
                self._drop(replica)
                continue
            finally:
                self._dec_inflight(aid)

    def _submit(self, method: str, args, kwargs, mux_id: str = "",
                session_id: str = "") -> DeploymentResponse:
        with self._lock:
            if self._router is None:
                self._router = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix=f"router-{self._name}")
            router = self._router
        deadline = time.monotonic() + 300.0
        # the submitter's trace context must ride into the router thread
        # as data — contextvars don't cross ThreadPoolExecutor hops
        trace_ctx = _tracing.current_context()
        fut = router.submit(self._route_blocking, method, args, kwargs,
                            deadline, mux_id, session_id, trace_ctx)
        return DeploymentResponse(fut)

    def _pick_replica_blocking(self, mux_id: str, deadline: float,
                               session_id: str = ""):
        """Block until some replica accepts (affinity/p2c + saturation
        backoff); raises TimeoutError at the deadline. The picked
        replica's in-flight count was already incremented by _pick."""
        saturated = 0
        while True:
            self._refresh()
            replica = self._pick(mux_id, session_id)
            if replica is not None:
                return replica
            if time.monotonic() > deadline:
                raise TimeoutError(f"{self._name}: no replica available")
            time.sleep(self._ROUTE_BACKOFF.backoff(saturated))
            saturated += 1
            self._refresh(force=True)

    def _dec_inflight(self, aid) -> None:
        with self._lock:
            c = self._inflight.get(aid, 0) - 1
            if c <= 0:
                self._inflight.pop(aid, None)
            else:
                self._inflight[aid] = c

    def _start_stream(self, method: str, args, kwargs, mux_id: str,
                      deadline: float, session_id: str = "",
                      trace_ctx=None):
        """-> (DeploymentResponseGenerator, replica). One routed
        streaming submission; the caller owns failover policy."""
        route_sid = None
        t_wall = time.time()
        if trace_ctx is not None:
            route_sid = _tracing.new_span_id()
            kwargs = {**kwargs,
                      _tracing.TRACE_KWARG: (trace_ctx[0], route_sid)}
        replica = self._pick_replica_blocking(mux_id, deadline, session_id)
        aid = replica._actor_id
        try:
            ref_gen = replica.handle_request_streaming.options(
                num_returns="streaming").remote(method, args, kwargs)
        finally:
            self._dec_inflight(aid)
            if trace_ctx is not None:
                # the route span covers replica pick + stream submission
                # (chunk pulls are the consumer's own timeline); engine
                # spans for this hop parent under route_sid
                slo = self._slo_target
                _tracing.record_span(
                    "serve.route", trace_ctx, t_wall, span_id=route_sid,
                    deployment=self._name, session=session_id,
                    streaming=True,
                    **({"slo_target": slo} if slo is not None else {}))
        return DeploymentResponseGenerator(ref_gen), replica

    def _submit_streaming(self, method: str, args, kwargs,
                          mux_id: str = "", resume=None,
                          session_id: str = ""):
        """Streaming requests route synchronously (picking a replica is
        cheap; the chunks themselves are pull-driven).

        Without ``resume`` they do NOT re-route mid-stream — a replica
        death surfaces to the consumer, matching the reference's
        streaming semantics (http_proxy.py:775). With a ``resume``
        callable the stream becomes failover-aware: on replica death the
        router, which tracked the request→replica assignment, rebuilds a
        continuation request via ``resume(args, kwargs, items_yielded)``
        and re-routes it to a surviving replica — the consumer sees a
        stall, never an error or a duplicated/lost item (the LLM serving
        path plugs its re-prefill semantics in here; see
        serve/llm/failover.py)."""
        if mux_id:
            from .multiplex import MUX_KWARG

            kwargs = {**kwargs, MUX_KWARG: mux_id}
        deadline = time.monotonic() + 300.0
        # captured HERE (the submitting thread still holds the proxy's
        # contextvar); it rides the generator as data because pulls may
        # happen from any thread
        trace_ctx = _tracing.current_context()
        if resume is not None:
            return FailoverResponseGenerator(self, method, args, kwargs,
                                             mux_id, resume, deadline,
                                             session_id,
                                             trace_ctx=trace_ctx)
        gen, _replica = self._start_stream(method, args, kwargs, mux_id,
                                           deadline, session_id,
                                           trace_ctx=trace_ctx)
        return gen

    def stream_assignments(self) -> Dict[int, Any]:
        """Live failover-stream → replica actor-id assignments (keyed by
        stream id); the observability hook chaos_smoke asserts on."""
        with self._lock:
            return dict(getattr(self, "_stream_assign", {}) or {})

    def session_assignments(self) -> Dict[str, Any]:
        """Live session → replica actor-id affinity pins (tests and the
        traffic harness assert stickiness/reroutes on this view)."""
        with self._lock:
            return dict(self._sessions)

    def _assign_stream(self, stream_key: int, aid) -> None:
        with self._lock:
            if not hasattr(self, "_stream_assign"):
                self._stream_assign: Dict[int, Any] = {}
            self._stream_assign[stream_key] = aid

    def _unassign_stream(self, stream_key: int) -> None:
        with self._lock:
            table = getattr(self, "_stream_assign", None)
            if table is not None:
                table.pop(stream_key, None)

    # -- public API ------------------------------------------------------------

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._submit("__call__", args, kwargs)

    def __getattr__(self, item: str):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def __repr__(self):
        return f"DeploymentHandle({self._name!r})"


class _MethodCaller:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._submit(self._method, args, kwargs)


class _OptionsHandle:
    """handle.options(stream=..., multiplexed_model_id=...,
    session_id=...) view — same underlying routing state, different
    submission mode."""

    def __init__(self, handle: DeploymentHandle, stream: bool,
                 mux_id: str, session_id: str = ""):
        self._handle = handle
        self._stream = stream
        self._mux_id = mux_id
        self._session_id = session_id

    def options(self, *, stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                session_id: Optional[str] = None) -> "_OptionsHandle":
        return _OptionsHandle(
            self._handle,
            self._stream if stream is None else stream,
            self._mux_id if multiplexed_model_id is None
            else multiplexed_model_id,
            self._session_id if session_id is None else session_id)

    def remote(self, *args, **kwargs):
        if self._stream:
            return self._handle._submit_streaming(
                "__call__", args, kwargs, self._mux_id,
                session_id=self._session_id)
        return self._handle._submit("__call__", args, kwargs,
                                    self._mux_id, self._session_id)

    def __getattr__(self, item: str):
        if item.startswith("_"):
            raise AttributeError(item)
        h, stream = self._handle, self._stream
        mux, sess = self._mux_id, self._session_id

        class _Caller:
            def remote(self, *args, **kwargs):
                if stream:
                    return h._submit_streaming(item, args, kwargs, mux,
                                               session_id=sess)
                return h._submit(item, args, kwargs, mux, sess)

        return _Caller()
