"""Replica actor — hosts one copy of the user's callable.

Equivalent of the reference's RayServeReplica (ref:
python/ray/serve/_private/replica.py — user callable wrapper, ongoing-
query counting, health checks, reconfigure). The TPU twist lives in
MeshDeployment (mesh_replica.py): a replica whose compute spans a gang of
mesh workers.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import cloudpickle


class Replica:
    def __init__(self, serialized_cls: bytes, init_args: tuple,
                 init_kwargs: dict, user_config: Any, deployment: str,
                 replica_tag: str, version: int):
        target = cloudpickle.loads(serialized_cls)
        self._deployment = deployment
        self._replica_tag = replica_tag
        self._version = version
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            # function deployment: args bind at call time
            self._callable = target
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request path ----------------------------------------------------------

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if method == "__call__":
                fn = self._callable
            else:
                fn = getattr(self._callable, method)
            return fn(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    # -- control plane ---------------------------------------------------------

    def ping(self) -> dict:
        """Health check; user classes may define check_health() that raises
        when unhealthy (ref: replica.py check_health)."""
        check = getattr(self._callable, "check_health", None)
        if callable(check):
            check()
        return {"ok": True, "version": self._version,
                "ongoing": self._ongoing, "total": self._total}

    def queue_len(self) -> int:
        return self._ongoing

    def reconfigure(self, user_config: Any) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if callable(fn):
            fn(user_config)
            return True
        return False

    def shutdown(self) -> bool:
        """Graceful cleanup before the controller hard-kills this actor —
        a MeshDeployment tears down its gang of mesh workers here."""
        fn = getattr(self._callable, "__del__", None)
        if callable(fn):
            fn()
        return True
