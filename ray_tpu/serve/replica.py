"""Replica actor — hosts one copy of the user's callable.

Equivalent of the reference's RayServeReplica (ref:
python/ray/serve/_private/replica.py — user callable wrapper, ongoing-
query counting, health checks, reconfigure). The TPU twist lives in
MeshDeployment (mesh_replica.py): a replica whose compute spans a gang of
mesh workers.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

from ..util import metrics as _metrics
from ..util import tracing as _tracing

# replica-side execution latency; lives in the replica worker's registry
# and ships to the head node/worker-tagged (util/metrics.py aggregation)
_H_REPLICA_EXEC = _metrics.Histogram(
    "ray_tpu_serve_replica_exec_seconds",
    "user-callable execution time inside a serve replica",
    tag_keys=("deployment",))


class Replica:
    def __init__(self, serialized_cls: bytes, init_args: tuple,
                 init_kwargs: dict, user_config: Any, deployment: str,
                 replica_tag: str, version: int):
        target = cloudpickle.loads(serialized_cls)
        self._deployment = deployment
        self._replica_tag = replica_tag
        self._version = version
        self._ongoing = 0
        self._total = 0
        self._draining = False
        self._lock = threading.Lock()
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            # function deployment: args bind at call time
            self._callable = target
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request path ----------------------------------------------------------

    def _resolve(self, method: str):
        return (self._callable if method == "__call__"
                else getattr(self._callable, method))

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        from .multiplex import MUX_KWARG, _current_model_id

        mux_id = kwargs.pop(MUX_KWARG, "")
        tctx = kwargs.pop(_tracing.TRACE_KWARG, None)
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = _current_model_id.set(mux_id)
        ttoken = None
        exec_sid = None
        if tctx is not None:
            # the route span's context (shipped as a reserved kwarg)
            # re-activates here so the user callable's own remote calls
            # and the LLM engine inherit it; the exec span parents them
            tctx = tuple(tctx)
            exec_sid = _tracing.new_span_id()
            ttoken = _tracing.activate((tctx[0], exec_sid))
        t0 = time.perf_counter()
        t_wall = time.time()
        err = ""
        try:
            return self._resolve(method)(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — re-raised
            err = type(e).__name__
            raise
        finally:
            _H_REPLICA_EXEC.observe(time.perf_counter() - t0,
                                    tags={"deployment": self._deployment})
            if ttoken is not None:
                _tracing.deactivate(ttoken)
                _tracing.record_span(
                    "replica.exec", tctx, t_wall, span_id=exec_sid,
                    deployment=self._deployment,
                    replica=self._replica_tag, method=method, error=err)
            _current_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method: str, args: tuple,
                                 kwargs: dict):
        """Generator variant: chunks ride the core streaming-returns
        protocol (ref: _private/replica.py handle_request_streaming;
        here num_returns='streaming' on this actor method does the
        backpressure + cancellation)."""
        from .multiplex import MUX_KWARG, _current_model_id

        mux_id = kwargs.pop(MUX_KWARG, "")
        tctx = kwargs.pop(_tracing.TRACE_KWARG, None)
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = _current_model_id.set(mux_id)
        try:
            if tctx is not None:
                # bracket ONLY the user-callable invocation (for the LLM
                # server this synchronously calls engine.add_request,
                # which captures the context onto the Request): a
                # contextvar left set across `yield` would leak into
                # whatever this worker thread runs between pulls
                tctx = tuple(tctx)
                exec_sid = _tracing.new_span_id()
                t_wall = time.time()
                ttoken = _tracing.activate((tctx[0], exec_sid))
                err = ""
                try:
                    result = self._resolve(method)(*args, **kwargs)
                except BaseException as e:  # noqa: BLE001 — re-raised
                    err = type(e).__name__
                    raise
                finally:
                    _tracing.deactivate(ttoken)
                    _tracing.record_span(
                        "replica.exec", tctx, t_wall, span_id=exec_sid,
                        deployment=self._deployment,
                        replica=self._replica_tag, method=method,
                        streaming=True, error=err)
            else:
                result = self._resolve(method)(*args, **kwargs)
            yield from result
        finally:
            _current_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1

    def multiplexed_model_ids(self) -> list:
        """Resident multiplexed models (router locality hints; ref:
        multiplex.py push of model ids through replica info)."""
        from .multiplex import resident_model_ids

        return resident_model_ids(self._callable)

    # -- control plane ---------------------------------------------------------

    def ping(self) -> dict:
        """Health check; user classes may define check_health() that raises
        when unhealthy (ref: replica.py check_health)."""
        check = getattr(self._callable, "check_health", None)
        if callable(check):
            check()
        info = {"ok": True, "version": self._version,
                "ongoing": self._ongoing, "total": self._total,
                "draining": self._draining}
        # user callables with their own backlog (the LLM engine's
        # waiting+running depth) expose queue_len(); shipping it in the
        # ping lets the controller autoscale on engine backlog, which
        # in-flight RPC counts undercount once requests stream
        qfn = getattr(self._callable, "queue_len", None)
        if callable(qfn):
            try:
                info["queue_depth"] = int(qfn())
            except Exception:
                pass
        # prefix-cache health (the LLM engine's cache_stats()): the
        # controller records cache_hit_rate / prefix_blocks_resident per
        # replica so the balancer can prefer cache-warm replicas and
        # scale-down can pick cache-cold victims (controller.py,
        # handle.py _warmth_map)
        cfn = getattr(self._callable, "cache_stats", None)
        if callable(cfn):
            try:
                cs = cfn()
                info["cache_hit_rate"] = float(cs.get("cache_hit_rate", 0.0))
                info["prefix_blocks_resident"] = int(
                    cs.get("prefix_blocks_resident", 0))
            except Exception:
                pass
        return info

    def queue_len(self) -> int:
        return self._ongoing

    def set_draining(self, flag: bool) -> bool:
        """Controller-set preemption-drain mark (docs/FAULT_TOLERANCE.md
        "Elasticity"): reported in every health ping, and forwarded to
        the user callable's ``drain()`` hook when it defines one (an
        LLM engine could stop admitting prompts, flush caches, ...)."""
        self._draining = bool(flag)
        hook = getattr(self._callable, "drain", None)
        if callable(hook):
            try:
                hook(self._draining)
            except Exception:
                pass
        return self._draining

    def reconfigure(self, user_config: Any) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if callable(fn):
            fn(user_config)
            return True
        return False

    def shutdown(self) -> bool:
        """Graceful cleanup before the controller hard-kills this actor —
        a MeshDeployment tears down its gang of mesh workers here."""
        fn = getattr(self._callable, "__del__", None)
        if callable(fn):
            fn()
        return True
