"""Job submission — run entrypoint commands on a live cluster.

Equivalent of the reference's job-submission plane (ref:
dashboard/modules/job/job_manager.py:516 JobManager, :140 JobSupervisor
— a detached per-job supervisor actor runs the entrypoint shell command
and the job table survives the submitting client). With the
single-controller design this is THE path for "cluster outlives the
driver" workflows: external clients submit over the head's TCP port
(see cli.py `submit`) and the supervisor actor + job KV records live on
the head.

Job state machine: PENDING -> RUNNING -> SUCCEEDED | FAILED | STOPPED.
"""
from __future__ import annotations

import json
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

_NS = "job"  # KV namespace for job records


def _kv():
    from .core import runtime as runtime_mod

    rt = runtime_mod.get_runtime()
    if hasattr(rt, "gcs"):  # head/driver process
        return (lambda k, v: rt.gcs.kv_put(k, v, namespace=_NS),
                lambda k: rt.gcs.kv_get(k, namespace=_NS),
                lambda p: rt.gcs.kv_keys(p, namespace=_NS))
    return (lambda k, v: rt.kv_put(k, v, namespace=_NS),
            lambda k: rt.kv_get(k, namespace=_NS),
            lambda p: rt.kv_keys(p, namespace=_NS))


def _record(job_id: str, **fields) -> Dict:
    put, get, _ = _kv()
    raw = get(job_id)
    rec = json.loads(raw.decode()) if raw else {}
    rec.update(fields)
    put(job_id, json.dumps(rec).encode())
    return rec


class JobSupervisor:
    """Detached actor owning one job's subprocess (ref: job_manager.py:140
    JobSupervisor.run — the entrypoint is a shell command; stdout/stderr
    are captured and the exit code decides SUCCEEDED/FAILED)."""

    def __init__(self, job_id: str, entrypoint: str,
                 env: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None):
        self._job_id = job_id
        self._entrypoint = entrypoint
        self._env = env or {}
        self._cwd = working_dir
        self._proc = None
        self._stop_requested = False
        _record(job_id, status="PENDING", entrypoint=entrypoint,
                submitted_at=time.time())

    def _self_destruct(self) -> None:
        """The supervisor exits once its job is terminal — detached actors
        are never GC'd, and a leaked 0.1-CPU actor per submitted job would
        starve a long-lived head. Delayed so run() returns cleanly first;
        the actor id must be captured NOW (the task context is gone by the
        time the timer fires)."""
        import threading

        try:
            actor_id = ray_tpu.get_runtime_context().actor_id
        except Exception:
            return
        if actor_id is None:
            return

        def _kill():
            try:
                from .core import runtime as runtime_mod

                runtime_mod.get_runtime().kill_actor(actor_id,
                                                     no_restart=True)
            except Exception:
                pass

        threading.Timer(0.5, _kill).start()

    def run(self) -> int:
        import os
        import subprocess

        if self._stop_requested:  # stopped while PENDING
            _record(self._job_id, status="STOPPED",
                    finished_at=time.time(), exit_code=-15, logs="")
            self._self_destruct()
            return -15
        env = dict(os.environ)
        env.update({str(k): str(v) for k, v in self._env.items()})
        env["RTPU_JOB_ID"] = self._job_id
        _record(self._job_id, status="RUNNING", started_at=time.time())
        try:
            # own process group: stop() must reach the shell's CHILDREN,
            # not just the /bin/sh wrapper (ref: job_manager.py:140 kills
            # the supervisor's whole process tree)
            self._proc = subprocess.Popen(
                self._entrypoint, shell=True, env=env, cwd=self._cwd,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, start_new_session=True)
            if self._stop_requested:
                # stop() ran in the other concurrency lane between the
                # PENDING check above and the Popen assignment — it saw
                # _proc None and could only set the flag; honor it now
                import signal

                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
                except Exception:
                    self._proc.terminate()
            out, _ = self._proc.communicate()
            rc = self._proc.returncode
        except Exception as e:  # spawn failure is a FAILED job, not a crash
            _record(self._job_id, status="FAILED", finished_at=time.time(),
                    exit_code=-1, logs=f"entrypoint failed to start: {e}")
            self._self_destruct()
            return -1
        _record(self._job_id,
                status=("SUCCEEDED" if rc == 0 else
                        "STOPPED" if rc < 0 else "FAILED"),
                finished_at=time.time(), exit_code=rc, logs=out or "")
        self._self_destruct()
        return rc

    def stop(self) -> bool:
        import os
        import signal

        if self._proc is None:
            # not launched yet: flag it so run() records STOPPED instead
            # of executing (the reference moves PENDING straight to STOPPED)
            self._stop_requested = True
            return True
        if self._proc.poll() is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except Exception:
                self._proc.terminate()
            return True
        return False

    def ping(self) -> bool:
        return True


def submit_job(entrypoint: str, *, env: Optional[Dict[str, str]] = None,
               working_dir: Optional[str] = None,
               job_id: Optional[str] = None) -> str:
    """-> job_id. The supervisor is detached: it outlives the submitter
    (ref: job_manager.py:516 submit_job)."""
    job_id = job_id or f"job-{uuid.uuid4().hex[:8]}"
    sup = ray_tpu.remote(JobSupervisor).options(
        name=f"_rtpu_job:{job_id}", lifetime="detached",
        num_cpus=0.1,
        # run() blocks for the whole job: stop()/ping() need their own lane
        max_concurrency=2).remote(job_id, entrypoint, env, working_dir)
    # fire-and-forget: the run() result lands in the job KV record
    sup.run.remote()
    return job_id


def get_job_status(job_id: str) -> Optional[str]:
    rec = get_job_info(job_id)
    return None if rec is None else rec.get("status")


def get_job_info(job_id: str) -> Optional[Dict]:
    _, get, _ = _kv()
    raw = get(job_id)
    return None if raw is None else json.loads(raw.decode())


def get_job_logs(job_id: str) -> str:
    rec = get_job_info(job_id) or {}
    return rec.get("logs", "")


def list_jobs() -> List[Dict]:
    _, get, keys = _kv()
    out = []
    for k in keys(""):
        raw = get(k)
        if raw:
            rec = json.loads(raw.decode())
            rec["job_id"] = k
            out.append(rec)
    return out


def stop_job(job_id: str) -> bool:
    try:
        sup = ray_tpu.get_actor(f"_rtpu_job:{job_id}")
        return ray_tpu.get(sup.stop.remote(), timeout=30)
    except Exception:
        return False


def wait_job(job_id: str, timeout: float = 300.0,
             poll_s: float = 0.25) -> Dict:
    """Block until the job reaches a terminal state; -> final record."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rec = get_job_info(job_id)
        if rec and rec.get("status") in ("SUCCEEDED", "FAILED", "STOPPED"):
            return rec
        time.sleep(poll_s)
    raise TimeoutError(f"job {job_id} still "
                       f"{(get_job_info(job_id) or {}).get('status')} "
                       f"after {timeout}s")
