"""ray_tpu.util — utility layer over the core API.

Parity with `ray.util` (ref: python/ray/util/__init__.py): ActorPool,
Queue, the multiprocessing.Pool shim, scheduling strategies, state API,
metrics, and the shared retry policy (util/retry.py — the one
backoff+jitter+deadline implementation graftcheck GC012 points at).
"""
from .actor_pool import ActorPool  # noqa: F401
from .queue import Queue  # noqa: F401
from .retry import RetryError, RetryPolicy, call_with_retry  # noqa: F401

__all__ = ["ActorPool", "Queue", "RetryPolicy", "RetryError",
           "call_with_retry"]
