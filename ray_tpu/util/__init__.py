"""ray_tpu.util — utility layer over the core API.

Parity with `ray.util` (ref: python/ray/util/__init__.py): ActorPool,
Queue, the multiprocessing.Pool shim, scheduling strategies, state API,
and metrics.
"""
from .actor_pool import ActorPool  # noqa: F401
from .queue import Queue  # noqa: F401

__all__ = ["ActorPool", "Queue"]
