"""State API — programmatic cluster introspection.

Equivalent of the reference's `ray.util.state` (ref:
python/ray/util/state/api.py list_tasks/list_actors/list_objects/
list_nodes; dashboard/state_aggregator.py). Backed by the head's GCS
tables, the task-event log, the reference counter, and per-node store
stats. Chrome-trace export mirrors `ray timeline`
(ref: scripts.py timeline command; task_event_buffer.h state events).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..core import runtime as runtime_mod


def _rt():
    rt = runtime_mod.get_runtime()
    if not hasattr(rt, "gcs"):
        raise RuntimeError("state API must run on the driver (head) process")
    return rt


def list_nodes() -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    for info in rt.gcs.nodes():
        node = rt.nodes.get(info.node_id)
        out.append({
            "node_id": info.node_id.hex(),
            "alive": info.alive,
            # preemption-notice drain state (docs/FAULT_TOLERANCE.md
            # "Elasticity"): alive but taking no new work
            "draining": bool(info.draining),
            "resources_total": dict(info.total_resources),
            "resources_available": (dict(node.available)
                                    if node is not None else {}),
            "labels": dict(info.labels),
            "is_remote": bool(getattr(node, "is_remote", False)),
            "num_workers": node.num_workers() if node is not None else 0,
            "lease_queue_len": node.queue_len() if node is not None else 0,
        })
    return out


def recent_logs(worker_id: Optional[str] = None,
                node_id: Optional[str] = None, pid: Optional[int] = None,
                limit: int = 500) -> List[Dict[str, Any]]:
    """Legacy tail of worker stdout/stderr captured on the head; see
    :func:`logs` for the attributed/filterable surface."""
    return _rt().recent_logs(worker_id=worker_id, node_id=node_id,
                             pid=pid, limit=limit)


def logs(job_id: Optional[str] = None, task_id: Optional[str] = None,
         actor_id: Optional[str] = None, worker_id: Optional[str] = None,
         node_id: Optional[str] = None, stream: Optional[str] = None,
         errors_only: bool = False, since: Optional[int] = None,
         limit: int = 500,
         follow_timeout: Optional[float] = None) -> Dict[str, Any]:
    """Attributed cluster log query (the `ray logs` analog): records
    carry {ts, node_id, worker_id, pid, job_id, task_id, actor_id,
    stream, level, seq, line}; id filters match hex prefixes. Returns
    {"records": [...], "cursor": n} — pass `since=cursor` (optionally
    with `follow_timeout`) to stream new lines."""
    return _rt().query_logs(job_id=job_id, task_id=task_id,
                            actor_id=actor_id, worker_id=worker_id,
                            node_id=node_id, stream=stream,
                            errors_only=errors_only, since=since,
                            limit=limit, follow_timeout=follow_timeout)


def stack_report(timeout: float = 5.0) -> Dict[str, Any]:
    """Merged thread stacks from the driver and every live worker
    (`ray stack` analog): {"driver": {...}, "workers": [{node_id,
    worker_id, pid, state, actor_id, threads|error}]}."""
    return _rt().stack_report(timeout_s=timeout)


def profile_worker(worker_id_prefix: str, duration_s: float = 5.0,
                   interval_s: float = 0.01) -> Dict[str, Any]:
    """On-demand sampling profile of one live worker; the result feeds
    introspect.profile_to_text / collapsed_to_text."""
    return _rt().profile_worker(worker_id_prefix, duration_s=duration_s,
                                interval_s=interval_s)


def log_store_stats() -> Dict[str, int]:
    """Retention counters of the head's log store (lines, bytes,
    evicted; the byte budget is config `log_store_max_bytes`)."""
    return _rt().gcs.logs.stats()


def traces(request_id: Optional[str] = None,
           session: Optional[str] = None,
           deployment: Optional[str] = None,
           slowest: Optional[int] = None, since: Optional[int] = None,
           limit: int = 50,
           follow_timeout: Optional[float] = None) -> Dict[str, Any]:
    """Completed request traces kept by the head's tail-sampler
    (docs/OBSERVABILITY.md "Distributed tracing"). Returns {"traces":
    [summaries], "cursor": n}; pass `since=cursor` (optionally with
    `follow_timeout`) to stream newly kept traces, or `slowest=N` for
    the N slowest retained."""
    return _rt().gcs.traces.query(request_id=request_id, session=session,
                                  deployment=deployment, slowest=slowest,
                                  since=since, limit=limit,
                                  follow_timeout=follow_timeout)


def trace_detail(trace_id_prefix: str) -> Optional[Dict[str, Any]]:
    """One trace's summary + full span list (`spans_detail`, time-
    ordered); the id may be a unique hex prefix — e.g. straight off a
    /metrics exemplar."""
    return _rt().gcs.traces.get(trace_id_prefix)


def trace_store_stats() -> Dict[str, Any]:
    """Retention counters of the head's trace store (kept, dropped by
    reason, bytes; the budget is config `trace_store_max_bytes`)."""
    return _rt().gcs.traces.stats()


def trace_chrome(trace_id_prefix: str,
                 output_path: Optional[str] = None) -> List[dict]:
    """One stored trace as chrome://tracing / Perfetto events — the
    same span-slice + cross-process flow-arrow shape as timeline()."""
    tr = _rt().gcs.traces.get(trace_id_prefix)
    if tr is None:
        return []
    trace = _span_trace_events(list(tr.get("spans_detail", ())))
    if output_path:
        with open(output_path, "w") as f:
            json.dump(trace, f)
    return trace


def actor_detail(actor_id_prefix: str) -> Optional[Dict[str, Any]]:
    """One actor's full picture: info + its recent task events + the
    log tail of its worker (dashboard drill-down)."""
    rt = _rt()
    for a in rt.gcs.list_actors():
        if a.actor_id.hex().startswith(actor_id_prefix):
            wid = a.worker_id.hex() if a.worker_id else None
            # exact actor_id match only: class-name substrings would pull
            # in sibling actors' events
            events = [e for e in rt.gcs.task_events()
                      if e.get("actor_id") == a.actor_id.hex()]
            return {
                "actor_id": a.actor_id.hex(), "name": a.name,
                "namespace": a.namespace, "state": a.state.name,
                "class_name": a.creation_spec.description.split(".")[0],
                "node_id": a.node_id.hex() if a.node_id else None,
                "worker_id": wid,
                "num_restarts": a.num_restarts,
                "death_cause": a.death_cause,
                "detached": a.detached,
                "recent_events": events[-50:],
                # attributed store: actor-stamped lines first (survives
                # worker restarts), worker tail as the fallback
                "logs": (rt.query_logs(actor_id=a.actor_id.hex(),
                                       limit=200)["records"]
                         or (rt.recent_logs(worker_id=wid, limit=200)
                             if wid else [])),
            }
    return None


def task_detail(task_id_prefix: str) -> Optional[Dict[str, Any]]:
    """One task's state transitions + lineage summary (dashboard
    drill-down)."""
    rt = _rt()
    events = [e for e in rt.gcs.task_events()
              if str(e.get("task_id", "")).startswith(task_id_prefix)]
    if not events:
        return None
    pend = None
    for tid, pt in list(rt.task_manager._pending.items()):
        if tid.hex().startswith(task_id_prefix):
            pend = {"state": pt.state, "retries_left": pt.retries_left}
            break
    return {"task_id": events[-1].get("task_id"),
            "name": events[-1].get("name"),
            "pending": pend, "events": events[-100:]}


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    for a in rt.gcs.list_actors():
        row = {
            "actor_id": a.actor_id.hex(),
            "name": a.name,
            "namespace": a.namespace,
            "state": a.state.name,
            "node_id": a.node_id.hex() if a.node_id else None,
            "num_restarts": a.num_restarts,
            "detached": a.detached,
            "death_cause": a.death_cause,
            "class_name": a.creation_spec.description.split(".")[0],
        }
        if state is None or row["state"] == state:
            out.append(row)
    return out


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Most-recent task state transitions (RUNNING/FINISHED/FAILED)."""
    rt = _rt()
    return rt.gcs.task_events()[-limit:]


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _rt()
    out = []
    directory, inline = rt.object_table_snapshot()
    for oid in list(inline)[:limit]:
        local, pins, holders = rt.refcount.counts(oid)
        out.append({"object_id": oid.hex(), "where": "inline",
                    "local_refs": local, "task_pins": pins,
                    "worker_refs": holders})
    for oid, nids in list(directory.items())[:max(0, limit - len(out))]:
        local, pins, holders = rt.refcount.counts(oid)
        out.append({"object_id": oid.hex(),
                    "where": [n.hex()[:12] for n in nids],
                    "local_refs": local, "task_pins": pins,
                    "worker_refs": holders})
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    rt = _rt()
    return [{
        "pg_id": pg.pg_id.hex(),
        "state": pg.state,
        "strategy": pg.strategy,
        "bundles": [dict(b) for b in pg.bundles],
        "nodes": [n.hex()[:12] if n else None for n in pg.bundle_nodes],
        "name": pg.name,
    } for pg in rt.gcs.list_pgs()]


def object_store_stats() -> Dict[str, Dict[str, Any]]:
    rt = _rt()
    out = {}
    for nid, node in rt.nodes.items():
        try:
            out[nid.hex()[:12]] = node.store.stats()
        except Exception:
            out[nid.hex()[:12]] = {}
    return out


def rpc_method_stats() -> Dict[str, dict]:
    """Per-RPC-method call/error/latency stats served by THIS process
    (ref: the reference's grpc_server_req_* metrics)."""
    from ..core.rpc import rpc_stats

    return rpc_stats()


def latency_summary() -> Dict[str, dict]:
    """p50/p95/p99 per latency histogram — task lifecycle phases, get(),
    store ops, RPC methods, serve — aggregated cluster-wide (worker- and
    agent-shipped series included). Backs /api/latency and
    `ray_tpu list latency`."""
    from . import metrics as metrics_mod

    return metrics_mod.latency_summary()


def summary() -> Dict[str, Any]:
    rt = _rt()
    events = rt.gcs.task_events()
    by_state: Dict[str, int] = {}
    for e in events:
        by_state[e.get("state", "?")] = by_state.get(e.get("state", "?"), 0) + 1
    return {
        "nodes_alive": sum(1 for n in rt.gcs.nodes() if n.alive),
        "nodes_total": len(rt.gcs.nodes()),
        "actors_by_state": _count_by(list_actors(), "state"),
        "task_events_by_state": by_state,
        "placement_groups": _count_by(list_placement_groups(), "state"),
        "object_store": object_store_stats(),
    }


def _count_by(rows: List[dict], key: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for r in rows:
        out[r[key]] = out.get(r[key], 0) + 1
    return out


def timeline(output_path: Optional[str] = None) -> List[dict]:
    """Chrome-trace (catapult) events from the task log; load the result
    in chrome://tracing or Perfetto (ref: `ray timeline`)."""
    rt = _rt()
    events = rt.gcs.task_events()
    starts: Dict[str, dict] = {}
    phases: Dict[str, Dict[str, float]] = {}  # tid -> {state: wall time}
    trace: List[dict] = []
    spans: List[dict] = []
    for e in events:
        tid = e.get("task_id", "")
        state = e.get("state")
        if state == "SPAN":
            spans.append(e)
        elif state in ("SUBMITTED", "SCHEDULED"):
            phases.setdefault(tid, {})[state] = e.get("time", 0.0)
        elif state == "RUNNING":
            starts[tid] = e
        elif state in ("FINISHED", "FAILED"):
            begin = starts.pop(tid, None)
            t_end = e.get("time", 0.0)
            t_begin = begin.get("time", t_end) if begin else t_end
            # phase breakdown joins the lifecycle events into the trace
            # slice: how long scheduling and the queue wait took before
            # this exec span started (straggler-phase triage args)
            args: Dict[str, Any] = {"state": state}
            marks = phases.pop(tid, {})
            t_sub = marks.get("SUBMITTED")
            t_sched = marks.get("SCHEDULED")
            if t_sub is not None and t_sched is not None:
                args["submit_to_sched_ms"] = round(
                    max(0.0, (t_sched - t_sub)) * 1e3, 3)
            queued_from = t_sched if t_sched is not None else t_sub
            if queued_from is not None and begin is not None:
                args["queue_wait_ms"] = round(
                    max(0.0, (t_begin - queued_from)) * 1e3, 3)
            args["exec_ms"] = round(max(0.0, (t_end - t_begin)) * 1e3, 3)
            trace.append({
                "name": e.get("name", tid[:8]),
                "cat": "task",
                "ph": "X",  # complete event
                "ts": t_begin * 1e6,
                "dur": max(1.0, (t_end - t_begin) * 1e6),
                "pid": e.get("node_id", "head")[:12],
                "tid": tid[:12],
                "args": args,
            })
    trace.extend(_span_trace_events(spans))
    if output_path:
        with open(output_path, "w") as f:
            json.dump(trace, f)
    return trace


def _span_trace_events(spans: List[dict]) -> List[dict]:
    """SPAN events -> chrome-trace slices + flow arrows.

    Spans from one OS process share a `spans pid=N` lane on their node's
    row, so sibling/child spans nest naturally by time containment;
    parent -> child edges that CROSS processes (submitter's span -> the
    task's span in a worker) are drawn as flow events (`ph: s/f`, bound
    by span_id), which Perfetto renders as arrows — the cross-worker
    call tree (satellite; ref: `ray timeline` + OTel span trees)."""
    out: List[dict] = []
    by_id = {s.get("span_id"): s for s in spans}

    def lane(s: dict) -> tuple:
        node = str(s.get("node_id") or "head")[:12]
        return node, f"spans pid={s.get('pid', '?')}"

    def bounds(s: dict) -> tuple:
        t0 = float(s.get("time") or 0.0)
        return t0, float(s.get("end_time") or t0)

    for s in spans:
        t0, t1 = bounds(s)
        pid, tid = lane(s)
        args = dict(s.get("attributes") or {})
        args["trace_id"] = s.get("trace_id")
        args["span_id"] = s.get("span_id")
        out.append({"name": s.get("name", "span"), "cat": "span",
                    "ph": "X", "ts": t0 * 1e6,
                    "dur": max(1.0, (t1 - t0) * 1e6),
                    "pid": pid, "tid": tid, "args": args})
        parent = by_id.get(s.get("parent_span_id"))
        if parent is None:
            continue
        p0, p1 = bounds(parent)
        ppid, ptid = lane(parent)
        # the flow start must land INSIDE the parent slice to attach
        anchor = min(max(t0, p0), p1)
        flow_id = str(s.get("span_id"))
        out.append({"name": "span-link", "cat": "span", "ph": "s",
                    "id": flow_id, "pid": ppid, "tid": ptid,
                    "ts": anchor * 1e6})
        out.append({"name": "span-link", "cat": "span", "ph": "f",
                    "bp": "e", "id": flow_id, "pid": pid, "tid": tid,
                    "ts": t0 * 1e6})
    return out
