"""Worker/driver log plane — capture, attribution, batching, mirroring.

Equivalent of the reference's log pipeline (ref:
python/ray/_private/log_monitor.py tails worker log files to the driver;
python/ray/_private/ray_logging.py structured worker logging). Here the
lines never touch disk: every worker process funnels stdout/stderr plus
the ``ray_tpu.logger`` structured channel through a :class:`LogBatcher`
that stamps each line with ``{stream, seq, ts, job_id, task_id,
actor_id, level}`` — attribution read from the worker's current-task
contextvar at *write* time, so interleaved async-actor lines never
mis-attribute — and ships bounded batches over the existing RPC channel.
Shipping is strictly non-blocking and rate-limited: past the budget,
lines are dropped and counted (``ray_tpu_logs_dropped_total``), never
buffered unboundedly and never allowed to stall the task.

The head ingests batches into the GCS :class:`~ray_tpu.core.log_store.
LogStore` and mirrors remote workers' lines onto the driver console with
a per-worker colored ``(worker pid=..., node=...)`` prefix and
repeated-line dedup (:class:`DriverMirror` — the ``log_to_driver``
analog of the reference's log_monitor -> driver mirroring).
"""
from __future__ import annotations

import logging as _pylogging
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics as _metrics

# stream names a record can carry; "log" is the structured logger channel
STREAMS = ("stdout", "stderr", "log")

LINES_TOTAL = _metrics.Counter(
    "ray_tpu_logs_lines_total",
    "log lines ingested into the head's attributed log store",
    tag_keys=("stream",))
DROPPED_TOTAL = _metrics.Counter(
    "ray_tpu_logs_dropped_total",
    "log lines dropped before reaching the head (rate limit, channel "
    "loss, store eviction)", tag_keys=("reason",))

# wire shape of one line inside a worker_log batch (a list, not a dict:
# a batch of hundreds of lines should not re-ship the key strings)
# [stream, seq, ts, job_id, task_id, actor_id, level, line]
REC_STREAM, REC_SEQ, REC_TS, REC_JOB, REC_TASK, REC_ACTOR, REC_LEVEL, \
    REC_LINE = range(8)


class LogBatcher:
    """Per-process accumulator for outbound log lines.

    ``emit()`` is called from arbitrary task/user threads (via the
    stdout/stderr tees and the structured logger handler); it stamps
    attribution + a per-stream monotonic ``seq`` and buffers. A flush —
    triggered by size, by the background timer, or explicitly — hands
    one wire payload to ``send`` (a channel ``notify``: enqueue-only,
    never blocking). A token-bucket rate limiter drops (and counts)
    lines over budget instead of ever blocking the writer.
    """

    def __init__(self, send: Callable[[dict], None],
                 task_ids: Optional[Callable[[], Tuple[str, str, str]]] = None,
                 batch_lines: int = 200,
                 flush_interval_s: float = 0.2,
                 rate_lines_per_s: float = 2000.0,
                 start_thread: bool = True):
        self._send = send
        self._task_ids = task_ids or (lambda: ("", "", ""))
        self._batch_lines = max(1, int(batch_lines))
        self._interval = max(0.01, float(flush_interval_s))
        self._rate = float(rate_lines_per_s)
        self._lock = threading.Lock()
        self._buf: List[list] = []
        self._seq: Dict[str, int] = {}
        self._dropped_pending = 0  # drops not yet reported in a payload
        self.dropped_total = 0
        # token bucket: capacity = 1s of budget (burst headroom)
        self._tokens = self._rate
        self._last_refill = time.monotonic()
        self._stop = threading.Event()
        if start_thread:
            threading.Thread(target=self._flush_loop, daemon=True,
                             name="log-flush").start()

    def emit(self, stream: str, lines: List[str], level: str = "") -> None:
        if not lines:
            return
        try:
            job, task, actor = self._task_ids()
        except Exception:
            job = task = actor = ""
        ts = time.time()
        flush_now = False
        with self._lock:
            dropped = 0
            if self._rate > 0:
                now = time.monotonic()
                self._tokens = min(
                    self._rate,
                    self._tokens + (now - self._last_refill) * self._rate)
                self._last_refill = now
                allowed = int(self._tokens)
                if allowed < len(lines):
                    dropped = len(lines) - allowed
                    self._dropped_pending += dropped
                    self.dropped_total += dropped
                    DROPPED_TOTAL.inc(dropped, tags={"reason": "rate"})
                    lines = lines[:allowed]
                self._tokens -= len(lines)
            seq = self._seq.get(stream, 0)
            for line in lines:
                self._buf.append(
                    [stream, seq, ts, job, task, actor, level, line])
                seq += 1
            # dropped lines still consume sequence numbers: a seq GAP in
            # the stored stream is the auditable drop signal
            self._seq[stream] = seq + dropped
            if len(self._buf) >= self._batch_lines:
                flush_now = True
        if flush_now:
            self.flush()

    def flush(self) -> None:
        # swap AND send under the lock: send only enqueues to the
        # channel's writer thread (never blocks), and two racing flushes
        # (size-triggered vs the timer) must not ship batches out of
        # order — the head relies on seq order within a stream
        failed = 0
        with self._lock:
            if not self._buf and not self._dropped_pending:
                return
            batch, self._buf = self._buf, []
            dropped, self._dropped_pending = self._dropped_pending, 0
            payload = {"pid": os.getpid(), "recs": batch}
            if dropped:
                payload["dropped"] = dropped
            try:
                self._send(payload)
            except Exception:
                # channel down: the local console still has the lines
                failed = len(batch)
                self.dropped_total += failed
        if failed:
            DROPPED_TOTAL.inc(failed, tags={"reason": "channel"})

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush()

    def stop(self) -> None:
        self._stop.set()
        self.flush()


class StreamTee:
    """Line-buffered tee of a process's stdout/stderr into a LogBatcher —
    the log plane's capture edge (ref: python/ray/_private/log_monitor.py
    tails worker log files; here lines ride the existing RPC channel).
    Local writes still reach the original stream (the parent console)."""

    def __init__(self, batcher: LogBatcher, stream: str, orig):
        self._batcher = batcher
        self._stream = stream
        self._orig = orig
        # per-thread partial-line buffers: print() writes the text and
        # the trailing "\n" as SEPARATE calls, so one shared buffer
        # would shear concurrent writers' fragments into each other —
        # each thread's line assembles privately and ships whole.
        # threading.local (not an ident-keyed dict): idents are REUSED
        # after a thread dies, which would splice a dead thread's
        # unterminated fragment into an unrelated thread's first line —
        # and the storage dies with its thread, so nothing leaks
        self._local = threading.local()
        # file-object surface libraries probe before writing
        self.encoding = getattr(orig, "encoding", "utf-8")
        self.errors = getattr(orig, "errors", "strict")

    def writelines(self, lines) -> None:
        for line in lines:
            self.write(line)

    @property
    def buffer(self):
        return getattr(self._orig, "buffer", self._orig)

    def write(self, s: str) -> int:
        self._orig.write(s)
        lines = None
        buf = getattr(self._local, "buf", "") + s
        if "\n" in buf:
            done, buf = buf.rsplit("\n", 1)
            lines = done.split("\n")
        self._local.buf = buf
        if lines:
            self._batcher.emit(self._stream, lines)
        return len(s)

    def flush(self) -> None:
        self._orig.flush()

    def isatty(self) -> bool:
        return False

    def fileno(self):
        return self._orig.fileno()


# ---------------------------------------------------------------------------
# driver-side mirroring (log_to_driver)

# the reference's worker-prefix palette (ray_constants: cyan family
# avoided so error text stays distinct); cycled per (node, pid)
_COLORS = (36, 35, 33, 32, 34, 96, 95, 94, 92, 93)


class DriverMirror:
    """Print remote workers' lines on the driver console with a colored
    ``(worker pid=..., node=...)`` prefix and consecutive-duplicate
    dedup ("repeated Nx") — the log_to_driver surface."""

    # worker churn (restarts, autoscaling) mints fresh pids forever; the
    # per-worker state tables evict oldest past this cap (the same
    # discipline as the agent log rings / REMOTE_SERIES_MAX)
    _STATE_MAX = 256

    def __init__(self, enabled: bool = True, color: Optional[bool] = None):
        self.enabled = enabled
        self._color = (sys.stdout.isatty() if color is None else color)
        self._color_idx: Dict[tuple, int] = {}
        self._color_next = 0
        self._lock = threading.Lock()
        # (node, pid, stream) -> [last_line, repeat_count, first_ts]
        self._last: Dict[tuple, list] = {}

    def _prefix(self, node: str, pid, stream: str) -> str:
        text = f"(worker pid={pid}, node={node[:8]}) "
        if not self._color:
            return text
        key = (node, pid)
        idx = self._color_idx.get(key)
        if idx is None:
            if len(self._color_idx) >= self._STATE_MAX:
                self._color_idx.pop(next(iter(self._color_idx)))
            idx = self._color_idx[key] = self._color_next % len(_COLORS)
            self._color_next += 1
        return f"\x1b[{_COLORS[idx]}m{text}\x1b[0m"

    # a run of identical lines reports its count when a different line
    # arrives, or at this cadence while the run is still going (a
    # forever-repeating heartbeat must not look like one silent line)
    _REPEAT_FLUSH_S = 2.0

    def emit(self, node: str, pid, stream: str, lines: List[str]) -> None:
        if not self.enabled or not lines:
            return
        # structured-logger lines surface on stderr like the reference's
        # worker-log mirroring (the rpdb banner rides this path)
        out = sys.stderr if stream in ("stderr", "log") else sys.stdout
        key = (node, pid, stream)
        to_print: List[str] = []
        now = time.monotonic()
        with self._lock:
            state = self._last.get(key)
            if state is None:
                if len(self._last) >= self._STATE_MAX:
                    self._last.pop(next(iter(self._last)))
                # [last_line, repeat_count, first_repeat_ts]
                state = self._last[key] = [None, 0, now]
            for line in lines:
                if line == state[0]:
                    if not state[1]:
                        state[2] = now
                    state[1] += 1
                    if now - state[2] >= self._REPEAT_FLUSH_S:
                        to_print.append(
                            f"... last line repeated {state[1]}x "
                            f"(ongoing)")
                        state[1] = 0
                    continue
                if state[1]:
                    to_print.append(
                        f"... last line repeated {state[1]}x")
                    state[1] = 0
                state[0] = line
                to_print.append(line)
        prefix = self._prefix(node, pid, stream)
        for line in to_print:
            print(prefix + line, file=out)  # graftcheck: disable=GC007


# ---------------------------------------------------------------------------
# the ray_tpu.logger structured channel

_logger_lock = threading.Lock()
_handler_installed = False


class _StructuredHandler(_pylogging.Handler):
    """Routes stdlib-logging records into the log plane: in a worker,
    through its LogBatcher (stream="log", level attached); on the
    driver, straight into the head's LogStore. Console output rides the
    stderr tee/stream either way, so nothing prints twice."""

    def emit(self, record: _pylogging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:
            return
        err = sys.stderr
        # console copy bypasses a tee: the structured record below is the
        # shipped one (stream="log" + level), not a second stderr line
        console = err._orig if isinstance(err, StreamTee) else err
        try:
            console.write(line + "\n")
        except Exception:
            pass
        try:
            from ..core import runtime as runtime_mod

            rt = runtime_mod.maybe_runtime()
            if rt is None:
                return
            batcher = getattr(getattr(rt, "worker", None),
                              "log_batcher", None)
            if batcher is not None:
                batcher.emit("log", [record.getMessage()],
                             level=record.levelname)
            elif hasattr(rt, "gcs") and getattr(rt.gcs, "logs", None) \
                    is not None:
                rt.gcs.logs.append([{
                    "ts": record.created,
                    "node_id": "driver", "worker_id": rt.worker_id.hex(),
                    "pid": os.getpid(), "job_id": rt.job_id.hex(),
                    "task_id": "", "actor_id": "", "stream": "log",
                    "level": record.levelname, "seq": -1,
                    "line": record.getMessage()}])
        except Exception:
            pass


def get_logger(name: str = "ray_tpu") -> _pylogging.Logger:
    """The structured log channel: a stdlib logger whose records land in
    the cluster log store with level + task attribution (and on the
    local console). Use inside tasks/actors exactly like logging."""
    global _handler_installed
    logger = _pylogging.getLogger(name)
    with _logger_lock:
        if not _handler_installed:
            root = _pylogging.getLogger("ray_tpu")
            handler = _StructuredHandler()
            handler.setFormatter(_pylogging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s"))
            root.addHandler(handler)
            root.setLevel(_pylogging.INFO)
            root.propagate = False
            _handler_installed = True
    return logger
