"""Distributed FIFO queue backed by an actor.

Parity with the reference's `ray.util.queue.Queue`
(ref: python/ray/util/queue.py — actor-backed asyncio queue with
put/get/qsize/empty/full and *_nowait* variants)."""
from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._items: List[Any] = []

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self._items) >= self.maxsize:
            return False
        self._items.append(item)
        return True

    def get(self) -> tuple:
        if not self._items:
            return (False, None)
        return (True, self._items.pop(0))

    def get_batch(self, max_items: int) -> List[Any]:
        out, self._items = (self._items[:max_items],
                            self._items[max_items:])
        return out

    def qsize(self) -> int:
        return len(self._items)


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        cls = ray_tpu.remote(_QueueActor)
        self.actor = cls.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put.remote(item)):
                return
            if not block:
                raise Full("Queue is full")
            if deadline is not None and time.monotonic() > deadline:
                raise Full("Queue put timed out")
            time.sleep(0.005)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.001
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty("Queue is empty")
            if deadline is not None and time.monotonic() > deadline:
                raise Empty("Queue get timed out")
            time.sleep(delay)
            delay = min(delay * 2, 0.05)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_batch(self, max_items: int = 64) -> List[Any]:
        return ray_tpu.get(self.actor.get_batch.remote(max_items))

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass
