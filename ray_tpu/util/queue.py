"""Distributed FIFO queue backed by an async actor.

Parity with the reference's `ray.util.queue.Queue`
(ref: python/ray/util/queue.py — the queue IS an asyncio.Queue inside an
async actor; blocking put/get are awaits parked on the actor's event
loop). No client-side polling: a blocked `get` costs one in-flight actor
call, not a wakeup loop — the difference between 10k parked consumers
and 10k × 200 RPCs/s of poll traffic (SURVEY §6 envelope).
"""
from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    """Async actor: every blocked producer/consumer is a parked coroutine
    on this actor's loop (ref: util/queue.py _QueueActor)."""

    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None) -> tuple:
        try:
            if timeout is None:
                return (True, await self._q.get())
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def get_nowait(self) -> tuple:
        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    async def get_batch(self, max_items: int) -> List[Any]:
        out: List[Any] = []
        while len(out) < max_items:
            try:
                out.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        return out

    async def qsize(self) -> int:
        return self._q.qsize()


class Queue:
    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        # parked producers/consumers each hold one concurrency slot
        opts.setdefault("max_concurrency", 1000)
        cls = ray_tpu.remote(_QueueActor)
        self.actor = cls.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full("Queue is full")
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full("Queue put timed out")

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("Queue is empty")
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty("Queue get timed out")
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_batch(self, max_items: int = 64) -> List[Any]:
        return ray_tpu.get(self.actor.get_batch.remote(max_items))

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass
