"""On-demand process introspection: thread stacks + sampling profiles.

Equivalent of the reference's ``ray stack`` (py-spy dump over every
worker on a node) and ``ray timeline``-adjacent profiling hooks. Workers
answer a ``dump_stacks`` RPC from :func:`dump_stacks` — a pure
``sys._current_frames()`` walk, safe to run while the main thread is
blocked in a ``get()`` — and a ``profile`` RPC from
:class:`SamplingProfiler`, a py-spy-style wall-clock sampler that
aggregates collapsed stacks (flamegraph text: ``frame;frame;frame N``)
plus a pstats-like self/cumulative table. Sampling, unlike cProfile's
tracing, needs no cooperation from the profiled threads and has
near-zero overhead between samples — the right trade for live
production workers (the exit-time cProfile dump behind
``RTPU_WORKER_PROFILE`` remains for offline runs).
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional


def dump_stacks() -> Dict[str, Any]:
    """Every thread's current stack in this process.

    -> {"pid", "threads": [{"thread_id", "name", "daemon", "frames":
    ["file:line in fn", ...] outermost-first}]}.
    """
    import os

    names = {t.ident: t for t in threading.enumerate()}
    threads = []
    for tid, frame in sys._current_frames().items():
        t = names.get(tid)
        frames = []
        for fs in traceback.extract_stack(frame):
            frames.append(f"{fs.filename}:{fs.lineno} in {fs.name}")
        threads.append({
            "thread_id": tid,
            "name": t.name if t is not None else f"thread-{tid}",
            "daemon": bool(t.daemon) if t is not None else False,
            "frames": frames,
        })
    threads.sort(key=lambda r: (r["daemon"], r["name"]))
    return {"pid": os.getpid(), "threads": threads}


def format_stacks(report: Dict[str, Any], header: str = "") -> str:
    """Render a dump_stacks() report like faulthandler / `ray stack`."""
    out = []
    if header:
        out.append(header)
    for th in report.get("threads", ()):
        out.append(f"  Thread {th['thread_id']} ({th['name']}"
                   f"{', daemon' if th['daemon'] else ''}):")
        for fr in th["frames"]:
            out.append(f"    {fr}")
    return "\n".join(out)


class SamplingProfiler:
    """Wall-clock sampler over every thread in this process."""

    def __init__(self, interval_s: float = 0.01):
        self.interval_s = max(0.001, float(interval_s))

    def run(self, duration_s: float,
            exclude_threads: Optional[set] = None) -> Dict[str, Any]:
        """Sample for ``duration_s``; -> {"samples", "duration_s",
        "interval_s", "collapsed": {stack_key: count},
        "functions": {frame_key: [self, cum]}}.

        ``stack_key`` is ``outer;...;inner`` (flamegraph collapsed
        format); ``frame_key`` is ``fn (file:line-of-def)``.
        """
        me = threading.get_ident()
        skip = {me} | set(exclude_threads or ())
        collapsed: Dict[str, int] = {}
        functions: Dict[str, List[int]] = {}
        samples = 0
        t0 = time.monotonic()
        deadline = t0 + max(0.0, float(duration_s))
        while True:
            for tid, frame in sys._current_frames().items():
                if tid in skip:
                    continue
                keys = []
                f = frame
                while f is not None:
                    code = f.f_code
                    keys.append(f"{code.co_name} "
                                f"({code.co_filename}:"
                                f"{code.co_firstlineno})")
                    f = f.f_back
                keys.reverse()  # outermost first
                stack_key = ";".join(k.split(" ")[0] for k in keys)
                collapsed[stack_key] = collapsed.get(stack_key, 0) + 1
                seen = set()
                for i, k in enumerate(keys):
                    row = functions.setdefault(k, [0, 0])
                    if i == len(keys) - 1:
                        row[0] += 1  # self: innermost frame
                    if k not in seen:
                        row[1] += 1  # cumulative: once per stack
                        seen.add(k)
            samples += 1
            now = time.monotonic()
            if now >= deadline:
                break
            time.sleep(min(self.interval_s, deadline - now))
        return {"samples": samples,
                "duration_s": round(time.monotonic() - t0, 4),
                "interval_s": self.interval_s,
                "collapsed": collapsed,
                "functions": functions}


def profile_to_text(result: Dict[str, Any], top: int = 25) -> str:
    """pstats-style table from a SamplingProfiler result: self/cum
    sample counts per function, heaviest self-time first."""
    samples = max(1, int(result.get("samples", 0)))
    rows = sorted(result.get("functions", {}).items(),
                  key=lambda kv: (-kv[1][0], -kv[1][1], kv[0]))
    out = [f"{result.get('samples', 0)} samples over "
           f"{result.get('duration_s', 0)}s "
           f"(interval {result.get('interval_s', 0)}s)",
           f"{'self%':>7} {'cum%':>7} {'self':>6} {'cum':>6}  function"]
    for key, (self_n, cum_n) in rows[:top]:
        out.append(f"{self_n / samples * 100:6.1f}% "
                   f"{cum_n / samples * 100:6.1f}% "
                   f"{self_n:6d} {cum_n:6d}  {key}")
    return "\n".join(out)


def collapsed_to_text(result: Dict[str, Any]) -> str:
    """Flamegraph collapsed-stack text (`flamegraph.pl` / speedscope
    input): one `frame;frame;frame count` line per distinct stack."""
    rows = sorted(result.get("collapsed", {}).items(),
                  key=lambda kv: -kv[1])
    return "\n".join(f"{stack} {n}" for stack, n in rows)
