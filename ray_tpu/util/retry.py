"""Unified retry policy: exponential backoff + jitter + deadline.

One policy object replaces the ad-hoc ``while True: try/except/sleep``
loops that had grown around every flaky boundary (dispatch fallback,
peer reconnect, agent registration, object-pull retry, serve routing).
The reference runtime centralizes the same way (ref:
src/ray/common/grpc_util.h ExponentialBackoff; python/ray/_private/
utils.py retry decorators) — one place owns the backoff curve, the
jitter, and the give-up rule, so graftcheck GC012 can flag every loop
that does not.

Two shapes:

- :func:`call_with_retry` — wrap one flaky callable::

      result = call_with_retry(
          lambda: connect(addr), policy=RetryPolicy(deadline_s=30),
          retry_on=(OSError,), description="agent->head connect")

- :meth:`RetryPolicy.sleeps` — migrate an existing loop without
  restructuring it: an iterator that sleeps the backoff schedule
  between iterations and stops when the deadline/attempt budget is
  spent (the loop body keeps its own success ``return``/``break``)::

      for attempt in policy.sleeps(interrupt=stop_event):
          try:
              return do_thing()
          except TransientError:
              continue
      raise TimeoutError(...)   # budget exhausted

Jitter is multiplicative-uniform (``sleep * uniform(1-j, 1+j)``) so
herds of retriers decorrelate without ever sleeping past
``max_backoff_s * (1+j)``. Policies are immutable and thread-safe;
every call gets its own attempt counter.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Optional, Tuple, Type


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff curve + give-up rule.

    initial_backoff_s: first sleep.
    multiplier: backoff growth per attempt.
    max_backoff_s: backoff ceiling (pre-jitter).
    jitter: fraction of the sleep randomized (0.2 => +/-20%).
    deadline_s: total wall-clock budget from the first attempt
        (None = unbounded by time).
    max_attempts: attempt budget (None = unbounded by count). At least
        one of deadline_s / max_attempts should bound the loop —
        a policy with neither retries forever (GC012 flags callers
        that hand-roll that shape).
    """

    initial_backoff_s: float = 0.01
    multiplier: float = 2.0
    max_backoff_s: float = 1.0
    jitter: float = 0.2
    deadline_s: Optional[float] = None
    max_attempts: Optional[int] = None

    def backoff(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Jittered sleep before retry number ``attempt`` (0-based)."""
        base = min(self.max_backoff_s,
                   self.initial_backoff_s * (self.multiplier ** attempt))
        if self.jitter <= 0:
            return base
        r = (rng or _process_rng()).uniform(1.0 - self.jitter,
                                            1.0 + self.jitter)
        return max(0.0, base * r)

    def sleeps(self, interrupt: Optional[threading.Event] = None,
               deadline: Optional[float] = None) -> Iterator[int]:
        """Yield attempt indices, sleeping the backoff schedule BETWEEN
        attempts; stop (without raising) when the deadline or attempt
        budget is spent, or when ``interrupt`` is set. ``deadline`` is
        an absolute ``time.monotonic()`` override for callers that
        already carry one."""
        if deadline is None and self.deadline_s is not None:
            deadline = time.monotonic() + self.deadline_s
        attempt = 0
        while True:
            if interrupt is not None and interrupt.is_set():
                return
            yield attempt
            attempt += 1
            if self.max_attempts is not None \
                    and attempt >= self.max_attempts:
                return
            delay = self.backoff(attempt - 1)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                delay = min(delay, remaining)
            if interrupt is not None:
                if interrupt.wait(delay):
                    return
            elif delay > 0:
                time.sleep(delay)


class RetryError(Exception):
    """call_with_retry exhausted its budget; ``last`` holds the final
    attempt's exception."""

    def __init__(self, description: str, attempts: int,
                 last: BaseException):
        super().__init__(
            f"{description or 'retried call'} failed after {attempts} "
            f"attempt(s): {type(last).__name__}: {last}")
        self.attempts = attempts
        self.last = last


def call_with_retry(fn: Callable[[], Any], *,
                    policy: RetryPolicy,
                    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                    description: str = "",
                    interrupt: Optional[threading.Event] = None,
                    on_retry: Optional[Callable[[int, BaseException],
                                                None]] = None) -> Any:
    """Run ``fn`` under ``policy``; re-raise the last error wrapped in
    :class:`RetryError` when the budget is spent. ``on_retry(attempt,
    err)`` fires before each backoff sleep (logging hook)."""
    last: Optional[BaseException] = None
    attempts = 0
    for attempt in policy.sleeps(interrupt=interrupt):
        attempts = attempt + 1
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — the whole point
            last = e
            if on_retry is not None:
                try:
                    on_retry(attempt, e)
                except Exception:
                    pass
    if last is None:
        raise RetryError(description, attempts,
                         TimeoutError("interrupted before first attempt"))
    raise RetryError(description, attempts, last) from last


_RNG_LOCK = threading.Lock()
_RNG: Optional[random.Random] = None


def _process_rng() -> random.Random:
    """Process-wide jitter source. Deliberately NOT the chaos plan's
    seeded RNG — jitter must stay decorrelated across processes, while
    chaos draws must replay identically."""
    global _RNG
    with _RNG_LOCK:
        if _RNG is None:
            _RNG = random.Random()
        return _RNG
