"""Prometheus metrics exposition.

Equivalent of the reference's metrics pipeline (ref:
src/ray/stats/metric_defs.cc:44 native metric definitions;
python/ray/_private/metrics_agent.py Prometheus exposition). Gauges are
computed from live runtime state at scrape time — no sampling loop to
drift — and exposed on a stdlib HTTP endpoint at /metrics.

Also the app-metric API: Counter/Gauge/Histogram
(ref: python/ray/util/metrics.py) registered into the same exposition.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..core import runtime as runtime_mod

_user_metrics_lock = threading.Lock()
_user_metrics: List["Metric"] = []


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: Dict[tuple, float] = {}
        self._lock = threading.Lock()
        with _user_metrics_lock:
            _user_metrics.append(self)

    def _key(self, tags: Optional[Dict[str, str]]) -> tuple:
        tags = tags or {}
        return tuple(tags.get(k, "") for k in self.tag_keys)

    kind = "gauge"


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Exposed as _sum/_count (enough for rate/mean panels)."""
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self._counts: Dict[tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1


def _fmt_tags(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in tags.items())
    return "{" + inner + "}"


def _render() -> str:
    lines: List[str] = []

    def emit(name: str, value, tags: Optional[Dict[str, str]] = None,
             help_: str = "", kind: str = "gauge") -> None:
        if help_:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{_fmt_tags(tags or {})} {value}")

    rt = runtime_mod.maybe_runtime()
    if rt is not None and hasattr(rt, "gcs"):
        nodes = rt.gcs.nodes()
        emit("ray_tpu_nodes_total", len(nodes), help_="cluster nodes")
        emit("ray_tpu_nodes_alive", sum(1 for n in nodes if n.alive))
        by_state: Dict[str, int] = {}
        for a in rt.gcs.list_actors():
            by_state[a.state.name] = by_state.get(a.state.name, 0) + 1
        lines.append("# HELP ray_tpu_actors actors by state")
        lines.append("# TYPE ray_tpu_actors gauge")
        for state, n in sorted(by_state.items()):
            emit("ray_tpu_actors", n, {"state": state})
        lines.append("# HELP ray_tpu_task_events_total task state "
                     "transitions since head start")
        lines.append("# TYPE ray_tpu_task_events_total counter")
        for state, n in sorted(rt.gcs.task_event_counts().items()):
            emit("ray_tpu_task_events_total", n, {"state": state})
        for nid, node in list(rt.nodes.items()):
            try:
                st = node.store.stats()
            except Exception:
                continue
            tags = {"node": nid.hex()[:12]}
            emit("ray_tpu_object_store_bytes_used", st.get("used", 0), tags)
            emit("ray_tpu_object_store_capacity_bytes",
                 st.get("capacity", 0), tags)
            emit("ray_tpu_object_store_objects", st.get("num_objects", 0),
                 tags)
            emit("ray_tpu_object_store_evictions_total",
                 st.get("num_evictions", 0), tags, kind="counter")
            emit("ray_tpu_object_store_spills_total",
                 st.get("num_spills", 0), tags, kind="counter")
    with _user_metrics_lock:
        metrics = list(_user_metrics)
    for m in metrics:
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        with m._lock:
            items = list(m._values.items())
            counts = dict(getattr(m, "_counts", {}))
        for k, value in items:
            tags = dict(zip(m.tag_keys, k))
            if isinstance(m, Histogram):
                emit(m.name + "_sum", value, tags)
                emit(m.name + "_count", counts.get(k, 0), tags)
            else:
                emit(m.name, value, tags)
    return "\n".join(lines) + "\n"


_server: Optional[ThreadingHTTPServer] = None


def start_metrics_server(host: str = "127.0.0.1",
                         port: int = 0) -> Tuple[str, int]:
    """Start (or return) the /metrics endpoint; -> (host, port)."""
    global _server
    if _server is not None:
        return _server.server_address[:2]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):  # noqa: N802
            if self.path.rstrip("/") in ("", "/metrics", "/-/healthy"):
                body = _render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

    _server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_server.serve_forever, daemon=True,
                     name="metrics-http").start()
    return _server.server_address[:2]


def stop_metrics_server() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
