"""Prometheus metrics exposition + cluster-wide aggregation.

Equivalent of the reference's metrics pipeline (ref:
src/ray/stats/metric_defs.cc:44 native metric definitions;
python/ray/_private/metrics_agent.py Prometheus exposition). Gauges are
computed from live runtime state at scrape time — no sampling loop to
drift — and exposed on a stdlib HTTP endpoint at /metrics.

Also the app-metric API: Counter/Gauge/Histogram
(ref: python/ray/util/metrics.py) registered into the same exposition.

Histograms are fully bucketed: `boundaries` (seconds, ascending) define
cumulative `_bucket{le="..."}` series (with the mandatory `+Inf`
terminal) next to `_sum`/`_count`, and `percentile(p)` interpolates
p50/p95/p99-style estimates straight from the bucket counts.

Cluster aggregation (the metrics-agent analog, ref:
python/ray/_private/metrics_agent.py): metrics registered in worker or
remote-agent processes never share this process's registry, so those
processes periodically ship *deltas* (`snapshot_deltas`) over their
existing RPC channel — workers after each task / on a 1 s cadence,
agents piggybacked on the heartbeat — and the head merges them
(`merge_remote`) into the single `/metrics` exposition with `node` /
`worker` tags. One scrape of the head sees the whole cluster.
"""
from __future__ import annotations

import threading
import time
import warnings
from bisect import bisect_left
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

# RLock: __init__ runs its whole body (including the super().__init__
# chain) inside one critical section, so concurrent first-constructions
# of the same name can't double-register or reset each other's state
_user_metrics_lock = threading.RLock()
_user_metrics: List["Metric"] = []
# name -> instance: re-constructing a metric with a name this process
# already registered returns the SAME object (state intact), so the
# blessed pattern of creating a Counter inside a task body neither
# leaks one Metric per call nor makes every flush scan an ever-growing
# registry. Keyed by name alone — one exposition family has one kind,
# so a Counter/Gauge/Histogram collision on a name is an error.
_metric_index: Dict[str, "Metric"] = {}

# general-purpose request/task latency buckets (seconds)
DEFAULT_BOUNDARIES: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# sub-millisecond-heavy paths: RPC handlers, shared-memory store ops
FAST_BOUNDARIES: Tuple[float, ...] = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 1.0, 2.5)


class Metric:
    def __new__(cls, name: str, *args, **kwargs):
        with _user_metrics_lock:
            existing = _metric_index.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}; one exposition "
                        f"family cannot carry two kinds")
                return existing  # __init__ no-ops via _registered
            obj = super().__new__(cls)
            _metric_index[name] = obj
            return obj

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        with _user_metrics_lock:
            if getattr(self, "_registered", False):
                return  # registry reuse: keep the existing series state
            self.name = name
            self.description = description
            self.tag_keys = tuple(tag_keys)
            self._values: Dict[tuple, float] = {}
            self._shipped: Dict[tuple, Any] = {}  # delta watermarks
            self._lock = threading.Lock()
            self._registered = True
            _user_metrics.append(self)

    def _key(self, tags: Optional[Dict[str, str]]) -> tuple:
        tags = tags or {}
        return tuple(tags.get(k, "") for k in self.tag_keys)

    def _delta(self) -> Optional[dict]:
        """Changes since the last snapshot, as a wire-safe dict (lists +
        primitives only); None when nothing changed. Used by worker/agent
        processes to ship their registry to the head."""
        series = []
        with self._lock:
            for k, v in self._values.items():
                last = self._shipped.get(k, 0.0)
                if self.kind == "gauge":
                    if k in self._shipped and last == v:
                        continue
                    self._shipped[k] = v
                    series.append([list(k), v])
                else:  # counter: ship the increment
                    if v == last:
                        continue
                    self._shipped[k] = v
                    series.append([list(k), v - last])
        if not series:
            return None
        return {"name": self.name, "kind": self.kind,
                "help": self.description, "tag_keys": list(self.tag_keys),
                "series": series}

    kind = "gauge"


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def total(self) -> float:
        """Sum across every tag combination, as counted in this process."""
        with self._lock:
            return sum(self._values.values())


class Gauge(Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Bucketed histogram: cumulative `_bucket{le=...}` series (with
    `+Inf`) plus `_sum`/`_count`. `boundaries` are inclusive upper
    bounds in ascending order; observations above the last boundary land
    in the `+Inf` overflow bucket."""
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        with _user_metrics_lock:
            bounds = tuple(float(b)
                           for b in (boundaries or DEFAULT_BOUNDARIES))
            if getattr(self, "_registered", False):
                # registry reuse: don't reset buckets/counts — but a
                # caller asking for different bucketing must not
                # silently get the old one
                if boundaries is not None and bounds != self.boundaries:
                    warnings.warn(
                        f"histogram {name!r} already registered with "
                        f"boundaries {self.boundaries}; ignoring "
                        f"{bounds}", RuntimeWarning, stacklevel=2)
                return
            if not bounds or list(bounds) != sorted(set(bounds)):
                # a failed construction must not leave the name mapped
                # to a half-built instance
                _metric_index.pop(name, None)
                raise ValueError(
                    f"histogram {name!r}: boundaries must be strictly "
                    f"ascending and non-empty, got {boundaries!r}")
            super().__init__(name, description, tag_keys)
            self.boundaries = bounds
            self._counts: Dict[tuple, int] = {}
            # per-series NON-cumulative bucket counts: len(bounds)+1
            # (last = overflow); cumulated only at render time
            self._buckets: Dict[tuple, List[int]] = {}
            # OpenMetrics exemplars: per series, per bucket index, the
            # LATEST (trace_id, value, ts) observed with one — a p99
            # bucket on the scrape links straight to a stored trace
            self._exemplars: Dict[tuple, Dict[int, tuple]] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None,
                exemplar: Optional[str] = None) -> None:
        """``exemplar`` is a trace id to pin to the bucket this sample
        lands in (rendered as `# {trace_id="..."} value ts`)."""
        k = self._key(tags)
        idx = bisect_left(self.boundaries, value)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1
            b = self._buckets.get(k)
            if b is None:
                b = self._buckets[k] = [0] * (len(self.boundaries) + 1)
            b[idx] += 1
            if exemplar:
                self._exemplars.setdefault(k, {})[idx] = (
                    str(exemplar), float(value), time.time())

    def percentile(self, p: float,
                   tags: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Estimate the p-th percentile (p in (0, 100]) by linear
        interpolation inside the bracketing bucket. tags=None aggregates
        across every tagged series; None when nothing was observed."""
        with self._lock:
            if tags is None:
                rows = list(self._buckets.values())
            else:
                b = self._buckets.get(self._key(tags))
                rows = [b] if b else []
            agg = [sum(col) for col in zip(*rows)] if rows else []
        return percentile_from_buckets(self.boundaries, agg, p)

    def _delta(self) -> Optional[dict]:
        series = []
        with self._lock:
            for k, b in self._buckets.items():
                s = self._values.get(k, 0.0)
                c = self._counts.get(k, 0)
                last = self._shipped.get(k)
                if last is None:
                    ds, dc, db = s, c, list(b)
                else:
                    ls, lc, lb = last
                    if c == lc:
                        continue
                    ds, dc = s - ls, c - lc
                    db = [x - y for x, y in zip(b, lb)]
                self._shipped[k] = (s, c, list(b))
                # exemplars ride as an OPTIONAL 4th element so heads
                # that predate them still unpack the delta; pop = each
                # exemplar ships once (the head keeps the latest seen).
                # str keys survive JSON/msgpack map round-trips intact.
                ex = self._exemplars.pop(k, None)
                if ex:
                    series.append([list(k), [ds, dc, db, {
                        str(i): list(v) for i, v in ex.items()}]])
                else:
                    series.append([list(k), [ds, dc, db]])
        if not series:
            return None
        return {"name": self.name, "kind": "histogram",
                "help": self.description, "tag_keys": list(self.tag_keys),
                "boundaries": list(self.boundaries), "series": series}


def percentile_from_buckets(boundaries: Sequence[float],
                            bucket_counts: Sequence[int],
                            p: float) -> Optional[float]:
    """p-th percentile (p in (0, 100]) from NON-cumulative bucket counts
    (len(boundaries)+1, last = +Inf overflow), linearly interpolated
    within the bracketing bucket. Observations in the overflow bucket
    clamp to the last finite boundary (their true magnitude is unknown)."""
    total = sum(bucket_counts)
    if total == 0 or not boundaries:
        return None
    target = max(1e-12, p / 100.0) * total
    cum = 0.0
    for i, c in enumerate(bucket_counts[:len(boundaries)]):
        if c == 0:
            continue
        if cum + c >= target:
            lo = boundaries[i - 1] if i > 0 else 0.0
            hi = boundaries[i]
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return float(boundaries[-1])


# ---- Prometheus text-format escaping (satellite: label values holding
# `"`, `\` or newlines previously produced an unparseable exposition) ----

def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(s: str) -> str:
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_tags(tags: Dict[str, str]) -> str:
    # empty label values are spec-equivalent to the label being absent —
    # skip them so merged cluster series stay tidy
    items = [(k, v) for k, v in tags.items() if v not in ("", None)]
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}"


def _fmt_val(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


# ---- cluster-wide aggregation (head side) ---------------------------------

_remote_lock = threading.Lock()
# name -> {"kind","help","tag_keys","boundaries","series":{tagvals: val}}
# histogram series value: [sum, count, [bucket_counts]]
_remote_metrics: Dict[str, dict] = {}
# per-family series cap: worker churn (container dedication, crash
# restarts, serve autoscaling) mints fresh worker ids forever; without a
# bound the head's scrape body and memory grow monotonically. Series are
# kept in last-update order and the stalest evicted past the cap.
REMOTE_SERIES_MAX = 2000


def merge_remote(deltas: List[dict], node: str = "",
                 worker: str = "") -> None:
    """Fold metric deltas shipped from a worker/agent process into the
    head's exposition, tagged with their origin node (and worker, when
    the origin is a worker process)."""
    if not deltas:
        return
    with _remote_lock:
        for d in deltas:
            try:
                name = d["name"]
                kind = d.get("kind", "gauge")
                fam = _remote_metrics.get(name)
                if fam is None:
                    fam = _remote_metrics[name] = {
                        "kind": kind, "help": d.get("help", ""),
                        "tag_keys": tuple(d.get("tag_keys", ())) +
                        ("node", "worker"),
                        "boundaries": tuple(d.get("boundaries", ()) or ()),
                        "series": {},
                    }
                if kind == "histogram" and fam["boundaries"] != tuple(
                        d.get("boundaries", ())):
                    continue  # incompatible bucketing: drop, don't corrupt
                for tagvals, val in d.get("series", ()):
                    key = tuple(tagvals) + (node, worker)
                    cur = fam["series"].pop(key, None)  # re-insert at
                    # the tail below: dict order doubles as recency, so
                    # the cap evicts the longest-untouched series first
                    if cur is None \
                            and len(fam["series"]) >= REMOTE_SERIES_MAX:
                        fam["series"].pop(next(iter(fam["series"])))
                    if kind == "gauge":
                        fam["series"][key] = float(val)
                    elif kind == "histogram":
                        ds, dc, db = val[0], val[1], val[2]
                        ex = val[3] if len(val) > 3 else None
                        if cur is None:
                            cur = [0.0, 0, [0] * len(db), {}]
                        elif len(cur) == 3:  # pre-exemplar shape
                            cur.append({})
                        cur[0] += ds
                        cur[1] += dc
                        if len(cur[2]) == len(db):
                            cur[2] = [x + y for x, y in zip(cur[2], db)]
                        if ex:
                            cur[3].update(ex)
                        fam["series"][key] = cur  # re-insert (recency)
                    else:  # counter
                        fam["series"][key] = (cur or 0.0) + float(val)
            except Exception:
                continue  # one malformed delta must not poison the rest


def carry_backlog(backlog: List[dict], cap: int = 100) -> List[dict]:
    """Shared ship-retry policy for delta exporters (worker post-task
    flush, agent heartbeat): append this snapshot to whatever failed to
    ship earlier, keeping only the newest `cap` deltas. snapshot_deltas
    advances watermarks, so deltas that don't ship must ride a bounded
    backlog or their observations silently vanish from the head."""
    return (backlog + snapshot_deltas())[-cap:]


def reset_remote_metrics() -> None:
    """Drop every worker/agent-shipped series. Called by
    ray_tpu.shutdown(): the origin processes are dead, and a re-init in
    the same process must not blend the old cluster's node/worker-tagged
    numbers into the new cluster's scrape."""
    with _remote_lock:
        _remote_metrics.clear()


def snapshot_deltas() -> List[dict]:
    """Collect every registered metric's changes since the last call —
    what a worker/agent process ships to the head."""
    with _user_metrics_lock:
        metrics = list(_user_metrics)
    out = []
    for m in metrics:
        try:
            d = m._delta()
        except Exception:
            d = None
        if d:
            out.append(d)
    return out


# ---- exposition ------------------------------------------------------------

class _Family:
    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str, help_: str):
        self.name = name
        self.kind = kind
        self.help = help_
        # (suffix, tags, value, exemplar-or-None); exemplar is
        # (trace_id, value, ts) attached only to histogram _bucket rows
        self.samples: List[Tuple[str, Dict[str, str], Any, Any]] = []

    def add(self, suffix: str, tags: Dict[str, str], value,
            exemplar=None) -> None:
        self.samples.append((suffix, tags, value, exemplar))


def _hist_samples(fam: _Family, tags: Dict[str, str],
                  boundaries: Sequence[float], buckets: Sequence[int],
                  total: float, count: int, exemplars=None) -> None:
    def _ex(i):
        if not exemplars:
            return None
        return exemplars.get(i) or exemplars.get(str(i))

    cum = 0
    for i, (b, c) in enumerate(zip(boundaries, buckets)):
        cum += c
        fam.add("_bucket", {**tags, "le": _fmt_val(float(b))}, cum, _ex(i))
    fam.add("_bucket", {**tags, "le": "+Inf"}, count, _ex(len(boundaries)))
    fam.add("_sum", tags, total)
    fam.add("_count", tags, count)


def _runtime_families(fams: "OrderedFams") -> None:
    from ..core import runtime as runtime_mod

    rt = runtime_mod.maybe_runtime()
    if rt is None or not hasattr(rt, "gcs"):
        return
    nodes = rt.gcs.nodes()
    fams.get("ray_tpu_nodes_total", "gauge", "cluster nodes").add(
        "", {}, len(nodes))
    fams.get("ray_tpu_nodes_alive", "gauge", "live cluster nodes").add(
        "", {}, sum(1 for n in nodes if n.alive))
    actors = fams.get("ray_tpu_actors", "gauge", "actors by state")
    by_state: Dict[str, int] = {}
    for a in rt.gcs.list_actors():
        by_state[a.state.name] = by_state.get(a.state.name, 0) + 1
    for state, n in sorted(by_state.items()):
        actors.add("", {"state": state}, n)
    evs = fams.get("ray_tpu_task_events_total", "counter",
                   "task state transitions since head start")
    for state, n in sorted(rt.gcs.task_event_counts().items()):
        evs.add("", {"state": state}, n)
    store_fams = [
        fams.get("ray_tpu_object_store_bytes_used", "gauge",
                 "shared-memory store bytes in use"),
        fams.get("ray_tpu_object_store_capacity_bytes", "gauge",
                 "shared-memory store capacity"),
        fams.get("ray_tpu_object_store_objects", "gauge",
                 "sealed objects resident per store"),
        fams.get("ray_tpu_object_store_evictions_total", "counter",
                 "LRU evictions per store"),
        fams.get("ray_tpu_object_store_spills_total", "counter",
                 "disk/remote spills per store"),
    ]
    keys = ("used", "capacity", "num_objects", "num_evictions", "num_spills")
    for nid, node in list(rt.nodes.items()):
        try:
            st = node.store.stats()
        except Exception:
            continue
        tags = {"node": nid.hex()[:12]}
        for fam, key in zip(store_fams, keys):
            fam.add("", tags, st.get(key, 0))


def _jax_families(fams: "OrderedFams") -> None:
    """Device-memory / compile-count gauges — only when the application
    already imported jax (a scrape must not pay the jax import)."""
    import sys

    if "jax" not in sys.modules:
        return
    try:
        jax = sys.modules["jax"]
        devices = jax.local_devices()
    except Exception:
        return
    fams.get("ray_tpu_jax_local_device_count", "gauge",
             "jax.local_devices() visible to the head").add(
        "", {}, len(devices))
    mem = fams.get("ray_tpu_jax_device_memory_bytes", "gauge",
                   "per-device memory_stats bytes (TPU/GPU backends)")
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
            if key in stats:
                mem.add("", {"device": str(d.id), "kind": key}, stats[key])
    n = _jax_compile_count()
    if n is not None:
        fams.get("ray_tpu_jax_compilations_total", "counter",
                 "XLA compilation events observed via jax.monitoring").add(
            "", {}, n)


_jax_compiles_lock = threading.Lock()
_jax_compiles: Optional[int] = None  # None until the listener installs
_jax_listener_state = "unset"  # unset | installed | failed


def _jax_compile_count() -> Optional[int]:
    global _jax_compiles, _jax_listener_state
    # registration happens under the lock: /metrics is served by a
    # ThreadingHTTPServer, and two concurrent first scrapes registering
    # two listeners would double-count every compile forever
    with _jax_compiles_lock:
        if _jax_listener_state == "installed":
            return _jax_compiles
        if _jax_listener_state == "failed":
            return None
        try:
            from jax._src import monitoring as _mon

            def _on_event(event: str, **kw) -> None:
                global _jax_compiles
                with _jax_compiles_lock:
                    if "compil" in event:
                        _jax_compiles = (_jax_compiles or 0) + 1

            _mon.register_event_listener(_on_event)
            _jax_listener_state = "installed"
            _jax_compiles = _jax_compiles or 0
            return _jax_compiles
        except Exception:
            _jax_listener_state = "failed"
            return None


class OrderedFams:
    def __init__(self):
        self._fams: "Dict[str, _Family]" = {}

    def get(self, name: str, kind: str, help_: str = "") -> _Family:
        fam = self._fams.get(name)
        if fam is None:
            fam = self._fams[name] = _Family(name, kind, help_)
        return fam

    def families(self) -> List[_Family]:
        return list(self._fams.values())


def _collect_families() -> List[_Family]:
    fams = OrderedFams()
    try:
        _runtime_families(fams)
    except Exception:
        pass
    try:
        _jax_families(fams)
    except Exception:
        pass
    with _user_metrics_lock:
        metrics = list(_user_metrics)
    for m in metrics:
        fam = fams.get(m.name, m.kind, m.description)
        with m._lock:
            items = list(m._values.items())
            counts = dict(getattr(m, "_counts", {}))
            buckets = {k: list(v)
                       for k, v in getattr(m, "_buckets", {}).items()}
            exemplars = {k: dict(v)
                         for k, v in getattr(m, "_exemplars", {}).items()}
        for k, value in items:
            tags = dict(zip(m.tag_keys, k))
            if isinstance(m, Histogram):
                _hist_samples(fam, tags, m.boundaries,
                              buckets.get(k, ()), value, counts.get(k, 0),
                              exemplars.get(k))
            else:
                fam.add("", tags, value)
    with _remote_lock:
        # histogram values are [sum, count, buckets] lists merge_remote
        # mutates in place — copy them INSIDE the lock or a concurrent
        # push can tear the render into a non-monotonic exposition
        remote = {name: {"kind": f["kind"], "help": f["help"],
                         "tag_keys": f["tag_keys"],
                         "boundaries": f["boundaries"],
                         "series": {
                             k: ([v[0], v[1], list(v[2]),
                                  dict(v[3]) if len(v) > 3 else {}]
                                 if f["kind"] == "histogram" else v)
                             for k, v in f["series"].items()}}
                  for name, f in _remote_metrics.items()}
    for name, f in remote.items():
        fam = fams.get(name, f["kind"], f["help"])
        for key, val in f["series"].items():
            tags = dict(zip(f["tag_keys"], key))
            if f["kind"] == "histogram":
                total, count, bks = val[0], val[1], val[2]
                _hist_samples(fam, tags, f["boundaries"], bks, total, count,
                              val[3] if len(val) > 3 else None)
            else:
                fam.add("", tags, val)
    return fams.families()


def _render() -> str:
    lines: List[str] = []
    for fam in _collect_families():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for suffix, tags, value, ex in fam.samples:
            line = f"{fam.name}{suffix}{_fmt_tags(tags)} {_fmt_val(value)}"
            if ex:
                # OpenMetrics exemplar: `# {trace_id="..."} value ts` —
                # the landing bucket links straight to the stored trace
                tid, ev, ets = ex[0], ex[1], ex[2]
                line += (f' # {{trace_id="{_escape_label_value(tid)}"}}'
                         f" {_fmt_val(float(ev))} {ets:.3f}")
            lines.append(line)
    return "\n".join(lines) + "\n"


def latency_summary() -> Dict[str, dict]:
    """p50/p95/p99 (+count/mean) per histogram family, aggregated across
    every series — local AND worker/agent-shipped — plus a per-series
    breakdown. Backs `/api/latency` and `ray_tpu list latency`."""
    acc: Dict[str, dict] = {}

    def fold(name, boundaries, tag_keys, key, total, count, bks):
        if not boundaries or count == 0:
            return
        f = acc.get(name)
        if f is None or len(f["boundaries"]) != len(boundaries):
            if f is not None:
                return
            f = acc[name] = {"boundaries": tuple(boundaries),
                             "agg": [0] * (len(boundaries) + 1),
                             "sum": 0.0, "count": 0, "series": []}
        f["agg"] = [x + y for x, y in zip(f["agg"], bks)]
        f["sum"] += total
        f["count"] += count
        tags = {k: v for k, v in zip(tag_keys, key) if v}
        f["series"].append((tags, total, count, list(bks)))

    with _user_metrics_lock:
        metrics = [m for m in _user_metrics if isinstance(m, Histogram)]
    for m in metrics:
        with m._lock:
            rows = [(k, m._values.get(k, 0.0), m._counts.get(k, 0),
                     list(b)) for k, b in m._buckets.items()]
        for k, total, count, bks in rows:
            fold(m.name, m.boundaries, m.tag_keys, k, total, count, bks)
    with _remote_lock:
        for name, f in _remote_metrics.items():
            if f["kind"] != "histogram":
                continue
            for key, val in f["series"].items():
                fold(name, f["boundaries"], f["tag_keys"], key,
                     val[0], val[1], list(val[2]))

    out: Dict[str, dict] = {}
    for name, f in acc.items():
        bounds = f["boundaries"]

        def pct(bks, p):
            v = percentile_from_buckets(bounds, bks, p)
            return None if v is None else round(v, 6)

        out[name] = {
            "count": f["count"],
            "mean": round(f["sum"] / f["count"], 6) if f["count"] else None,
            "p50": pct(f["agg"], 50), "p95": pct(f["agg"], 95),
            "p99": pct(f["agg"], 99),
            "series": [
                {"tags": tags, "count": count,
                 "mean": round(total / count, 6) if count else None,
                 "p50": pct(bks, 50), "p95": pct(bks, 95),
                 "p99": pct(bks, 99)}
                for tags, total, count, bks in f["series"]],
        }
    return out


_server_lock = threading.Lock()
_server: Optional[ThreadingHTTPServer] = None
_server_requested: Optional[Tuple[str, int]] = None


def start_metrics_server(host: str = "127.0.0.1",
                         port: int = 0) -> Tuple[str, int]:
    """Start the /metrics endpoint; -> (host, port).

    Singleton per process: the first call binds, every later call
    returns the existing server's address. A later call naming a
    *different* host or explicit port is almost certainly a config
    error (the caller would silently scrape the wrong address), so it
    warns and keeps the original binding; call stop_metrics_server()
    first to rebind."""
    global _server, _server_requested
    with _server_lock:  # two first-calls racing must not double-bind
        if _server is not None:
            bound = _server.server_address[:2]
            if (host != _server_requested[0]
                    or (port != 0 and port != bound[1])):
                warnings.warn(
                    f"metrics server already bound to "
                    f"{bound[0]}:{bound[1]}; ignoring request for "
                    f"{host}:{port} (stop_metrics_server() first to "
                    f"rebind)", RuntimeWarning, stacklevel=2)
            return bound

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") in ("", "/metrics", "/-/healthy"):
                    body = _render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

        _server = ThreadingHTTPServer((host, port), Handler)
        _server_requested = (host, port)
        threading.Thread(target=_server.serve_forever, daemon=True,
                         name="metrics-http").start()
        return _server.server_address[:2]


def stop_metrics_server() -> None:
    global _server, _server_requested
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server = None
            _server_requested = None
