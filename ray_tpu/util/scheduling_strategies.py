"""Scheduling strategy objects (ref: python/ray/util/scheduling_strategies.py)."""
from __future__ import annotations

from typing import Optional

from ..core.ids import NodeId
from ..core.placement_group import PlacementGroup
from ..core.task_spec import SchedulingStrategy


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        if isinstance(node_id, str):
            node_id = NodeId.from_hex(node_id)
        self.node_id = node_id
        self.soft = soft

    def to_spec(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="NODE_AFFINITY", node_id=self.node_id,
                                  soft=self.soft)


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def to_spec(self) -> SchedulingStrategy:
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=self.placement_group.id,
            bundle_index=self.placement_group_bundle_index)
