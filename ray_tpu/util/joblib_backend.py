"""joblib parallel backend over the task plane.

ref: python/ray/util/joblib/__init__.py (+ ray_backend.py): registering
a joblib backend lets unmodified scikit-learn / joblib.Parallel code
fan out over the cluster with a context manager:

    import joblib
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel()(delayed(f)(x) for x in xs)   # runs as ray_tpu tasks

Each joblib batch (a BatchedCalls callable) ships as ONE task through
cloudpickle; completion callbacks fire from a small waiter thread so
joblib's auto-batching dispatch loop keeps feeding the cluster."""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import cloudpickle

import ray_tpu


def _run_joblib_batch(blob: bytes):
    """Worker side: rehydrate the BatchedCalls and run it."""
    return cloudpickle.loads(blob)()


class _TaskResult:
    """joblib future contract: .get(timeout) -> result; the callback
    fires when the task completes (from the waiter thread)."""

    def __init__(self, ref, callback: Optional[Callable]):
        self._ref = ref
        self._callback = callback
        self._done = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        threading.Thread(target=self._wait, daemon=True).start()

    def _wait(self):
        try:
            self._value = ray_tpu.get(self._ref)
        except BaseException as e:  # noqa: BLE001 — surfaced via get()
            self._error = e
        self._done.set()
        if self._callback is not None and self._error is None:
            self._callback(self._value)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("joblib task timed out")
        if self._error is not None:
            raise self._error
        return self._value


def register_ray_tpu() -> None:
    """Register the "ray_tpu" joblib backend (idempotent)."""
    from joblib import parallel

    if "ray_tpu" in getattr(parallel, "BACKENDS", {}):
        return

    from joblib._parallel_backends import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        # one joblib batch = one task; let joblib's auto-batching
        # decide batch sizes from measured task duration
        supports_retrieve_callback = False

        def configure(self, n_jobs: int = 1, parallel=None, **kw):
            self.parallel = parallel
            # one RemoteFunction for the whole Parallel run: its
            # per-runtime submit caches (func export, wire template)
            # exist precisely because submission is the hot path
            self._fn = ray_tpu.remote(_run_joblib_batch)
            return self.effective_n_jobs(n_jobs)

        @staticmethod
        def _cluster_cpus() -> int:
            try:
                return max(1, int(
                    ray_tpu.cluster_resources().get("CPU", 1)))
            except Exception:
                return 1

        def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
            if n_jobs is None:
                return 1
            if n_jobs < 0:
                # joblib convention: -1 = all CPUs, -2 = all but one...
                return max(1, self._cluster_cpus() + 1 + int(n_jobs))
            return max(1, int(n_jobs))

        def apply_async(self, func: Callable, callback=None):
            fn = getattr(self, "_fn", None)
            if fn is None:
                fn = self._fn = ray_tpu.remote(_run_joblib_batch)
            ref = fn.remote(cloudpickle.dumps(func))
            return _TaskResult(ref, callback)

        # joblib >= 1.4 prefers submit(); same contract
        def submit(self, func: Callable, callback=None):
            return self.apply_async(func, callback)

        def abort_everything(self, ensure_ready: bool = True):
            pass  # outstanding tasks finish; refs are dropped

    parallel.register_parallel_backend("ray_tpu", RayTpuBackend)
