"""multiprocessing.Pool shim over tasks.

Parity with the reference's `ray.util.multiprocessing.Pool`
(ref: python/ray/util/multiprocessing/pool.py — drop-in Pool whose
workers are actors, so existing `from multiprocessing import Pool` code
scales past one host by changing the import)."""
from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    """ref: pool.py AsyncResult — get/wait/ready/successful."""

    def __init__(self, refs: List[Any], single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    """Task-backed process pool. `processes` bounds in-flight tasks (the
    cluster's CPUs bound real parallelism); initializer runs inside each
    task via a lazily-applied wrapper since tasks are stateless here."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        self._processes = processes or int(
            ray_tpu.cluster_resources().get("CPU", 1))
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _wrap(self, func: Callable) -> Callable:
        init, initargs = self._initializer, self._initargs
        if init is None:
            return func

        def wrapped(*a, **kw):
            init(*initargs)
            return func(*a, **kw)

        wrapped.__name__ = getattr(func, "__name__", "pool_task")
        return wrapped

    def _submit_all(self, func: Callable, iterables,
                    chunksize: Optional[int] = None) -> List[Any]:
        if self._closed:
            raise ValueError("Pool not running")
        items = list(zip(*iterables)) if len(iterables) > 1 \
            else [(x,) for x in iterables[0]]
        if chunksize and chunksize > 1:
            chunks = [items[i:i + chunksize]
                      for i in range(0, len(items), chunksize)]

            def run_chunk(chunk, _fn=func, _init=self._initializer,
                          _initargs=self._initargs):
                if _init is not None:
                    _init(*_initargs)
                return [_fn(*args) for args in chunk]

            chunk_fn = ray_tpu.remote(run_chunk)
            return [chunk_fn.remote(c) for c in chunks], True
        remote_fn = ray_tpu.remote(self._wrap(func))
        return [remote_fn.remote(*args) for args in items], False

    @staticmethod
    def _flatten(results, chunked: bool):
        if not chunked:
            return results
        return list(itertools.chain.from_iterable(results))

    # -- the multiprocessing.Pool surface ---------------------------------

    def apply(self, func: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        if self._closed:
            raise ValueError("Pool not running")
        remote_fn = ray_tpu.remote(self._wrap(func))
        return AsyncResult([remote_fn.remote(*args, **(kwds or {}))],
                           single=True)

    def map(self, func: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        refs, chunked = self._submit_all(func, [iterable], chunksize)
        return self._flatten(ray_tpu.get(refs), chunked)

    def map_async(self, func: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        refs, chunked = self._submit_all(func, [iterable], chunksize)
        if chunked:
            raise NotImplementedError("map_async with chunksize")
        return AsyncResult(refs)

    def starmap(self, func: Callable, iterable: Iterable) -> List[Any]:
        # one wrapper for the whole batch: a fresh remote fn per item
        # would defeat the export cache (re-pickle + re-export per call)
        remote_fn = ray_tpu.remote(self._wrap(func))
        refs = [remote_fn.remote(*args) for args in iterable]
        return ray_tpu.get(refs)

    def imap(self, func: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        refs, chunked = self._submit_all(func, [iterable], chunksize)
        for r in refs:
            v = ray_tpu.get(r)
            if chunked:
                yield from v
            else:
                yield v

    def imap_unordered(self, func: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        refs, chunked = self._submit_all(func, [iterable], chunksize)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            v = ray_tpu.get(ready[0])
            if chunked:
                yield from v
            else:
                yield v

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
