"""Shared fsspec URL helper — neutral ground for the layers that take
storage URLs (tune syncer uploads, object-store spill tier), so core
never imports from a library package."""
from __future__ import annotations


def split_fs_url(uri: str):
    """-> (fsspec filesystem or None for plain-local, root path)."""
    if "://" not in uri:
        return None, uri
    import fsspec

    fs, _, paths = fsspec.get_fs_token_paths(uri)
    return fs, paths[0] if paths else uri.split("://", 1)[1]
