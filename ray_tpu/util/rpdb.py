"""Remote pdb — debug a worker process over a TCP socket.

ref: python/ray/util/rpdb.py (the reference wires its remote debugger
through GCS + the `ray debug` CLI; this is the direct-socket reduction:
the breakpoint prints its address to the worker log, and any `nc`/
`telnet` session gets a full pdb prompt).

    from ray_tpu.util.rpdb import set_trace

    @ray_tpu.remote
    def task():
        set_trace()        # blocks until a debugger client attaches

Then from any shell on the host:  nc 127.0.0.1 <printed port>
"""
from __future__ import annotations

import pdb
import socket
import sys
from typing import Optional


class _SocketIO:
    """File-ish adapter over a connected socket for Pdb's stdin/stdout."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._rfile = conn.makefile("r", encoding="utf-8")
        self._wfile = conn.makefile("w", encoding="utf-8")

    def readline(self) -> str:
        return self._rfile.readline()

    def write(self, data: str) -> int:
        self._wfile.write(data)
        return len(data)

    def flush(self) -> None:
        try:
            self._wfile.flush()
        except (BrokenPipeError, OSError):
            pass

    def close(self) -> None:
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass
        try:
            self._conn.close()
        except OSError:
            pass


class RemotePdb(pdb.Pdb):
    """Pdb bound to a TCP listener; one client per breakpoint hit.
    __init__ only BINDS (so `addr` is readable before any client
    exists); interact() blocks in accept() and runs the session."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 quiet: bool = False):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1)
        self.addr = self._listener.getsockname()
        self._quiet = quiet
        self._io: Optional[_SocketIO] = None

    def interact(self, frame) -> None:
        if not self._quiet:
            # the structured channel carries the banner to the driver's
            # log store with task attribution (the operator needs the
            # connect address even when this worker's console is remote)
            from .logs import get_logger

            get_logger("ray_tpu.rpdb").warning(
                "RemotePdb waiting on %s:%s (connect with: nc %s %s)",
                self.addr[0], self.addr[1], self.addr[0], self.addr[1])
        conn, _ = self._listener.accept()
        self._io = _SocketIO(conn)
        super().__init__(stdin=self._io, stdout=self._io)
        self.prompt = "(rpdb) "
        self.set_trace(frame)

    def do_continue(self, arg):
        out = super().do_continue(arg)
        if not self.breaks:
            # no breakpoints pending: the session is over. With
            # breakpoints set, the socket stays open — the next hit
            # prompts over the SAME connection
            self._close()
        return out

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        out = super().do_quit(arg)
        self._close()
        return out

    do_q = do_exit = do_quit

    def do_EOF(self, arg):  # noqa: N802 — pdb naming
        """Client disconnected (Ctrl-C on nc, dropped connection):
        release the sockets instead of leaking them for the worker's
        lifetime, then quit the session."""
        self._close()
        return super().do_quit(arg)

    def _close(self) -> None:
        if self._io is not None:
            self._io.close()
        try:
            self._listener.close()
        except OSError:
            pass


def set_trace(host: str = "127.0.0.1", port: int = 0,
              quiet: bool = False, frame: Optional[object] = None,
              _debugger_box: Optional[dict] = None) -> None:
    """Open a remote pdb session and break at the caller's frame.
    Blocks until a client connects (nc/telnet). `_debugger_box`, if
    given, receives the RemotePdb instance before blocking (tests read
    the bound address from it)."""
    debugger = RemotePdb(host=host, port=port, quiet=quiet)
    if _debugger_box is not None:
        _debugger_box["debugger"] = debugger
    debugger.interact(frame or sys._getframe().f_back)
