"""Distributed tracing — trace/span propagation across tasks and actors.

Equivalent of the reference's tracing hooks (ref: python/ray/util/
tracing/tracing_helper.py — OTel context injected into task metadata and
re-activated in the worker). Framework-free implementation: a trace
context (trace_id, span_id) lives in a contextvar, rides every TaskSpec
submitted under it, and is re-activated around remote execution; each
task execution emits a span into the GCS task-event stream, so
`timeline()` and the state API can reconstruct cross-process call trees
without an OTel dependency (plug a real exporter in via `span_export`).

    with tracing.trace("ingest") as span:
        ray_tpu.get(process.remote(x))   # child spans link to `span`

    tree = tracing.get_trace(span.trace_id)
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_current: "contextvars.ContextVar[Optional[tuple]]" = contextvars.ContextVar(
    "rtpu_trace_ctx", default=None)

# optional exporter hook: called with each finished span dict
span_export: Optional[Callable[[dict], None]] = None

# span-export failures are never allowed to break user code, but they
# must not vanish either: every swallowed failure counts here (shipped
# to the head's /metrics from workers) and the FIRST one per process
# warns with the cause (satellite: _record used to drop silently)
from . import metrics as _metrics  # noqa: E402

SPANS_DROPPED = _metrics.Counter(
    "ray_tpu_spans_dropped_total",
    "trace spans dropped before reaching the task-event stream",
    tag_keys=("reason",))

# head-side: whole traces dropped by the TraceStore — tail-sampled out
# ("sampled"), evicted under the byte budget ("evicted"), or spans
# arriving for an already-dropped trace ("late")
TRACES_DROPPED = _metrics.Counter(
    "ray_tpu_traces_dropped_total",
    "whole traces dropped by the head trace store",
    tag_keys=("reason",))
_warned_reasons: set = set()


def _note_span_drop(reason: str, err: BaseException) -> None:
    SPANS_DROPPED.inc(tags={"reason": reason})
    if reason not in _warned_reasons:
        _warned_reasons.add(reason)
        import warnings

        warnings.warn(
            f"tracing: span {reason} export failed ({err!r}); further "
            f"failures are counted in ray_tpu_spans_dropped_total "
            f"without warning", RuntimeWarning, stacklevel=3)


def _new_id() -> str:
    return os.urandom(8).hex()


def new_trace_id() -> str:
    """Fresh W3C-width (16-byte) trace id for ingress root spans."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    return _new_id()


# reserved kwarg carrying (trace_id, parent_span_id) across the
# handle -> replica actor hop (popped in replica.handle_request*, the
# MUX_KWARG pattern) — contextvars don't cross process boundaries
TRACE_KWARG = "__rtpu_trace__"


# ---- W3C trace-context wire format -----------------------------------------
# traceparent: 00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>
# (https://www.w3.org/TR/trace-context/). Internal ids are 8-byte hex;
# format_traceparent left-pads so an internally-rooted trace still
# round-trips through a W3C-conformant proxy or client.

def parse_traceparent(header: Optional[str]) -> Optional[tuple]:
    """traceparent header -> (trace_id, span_id) context, or None when
    absent/malformed (a bad header must not fail the request)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4 or parts[0] == "ff":
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
    except ValueError:
        return None
    return (trace_id, span_id)


def format_traceparent(ctx: tuple, sampled: bool = True) -> str:
    """(trace_id, span_id) -> a version-00 traceparent header value."""
    trace_id = str(ctx[0]).rjust(32, "0")
    span_id = str(ctx[1]).rjust(16, "0")
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def record_span(name: str, ctx: tuple, start: float,
                end: Optional[float] = None,
                span_id: Optional[str] = None, **attributes) -> str:
    """Emit one finished span explicitly, without touching the
    contextvar — for code that crosses threads (router pool, engine
    scheduler loop) where the trace context travels as data, not
    ambient state. ``ctx`` is the PARENT (trace_id, parent_span_id);
    returns the new span's id so callers can parent further children."""
    sid = span_id or _new_id()
    span = Span(trace_id=ctx[0], span_id=sid, parent_span_id=ctx[1],
                name=name, start=start, end=end if end is not None
                else time.time(), attributes=dict(attributes))
    _record(span)
    return sid


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_span_id: Optional[str]
    name: str
    start: float = field(default_factory=time.time)
    end: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[str(key)] = value


class trace:
    """Context manager opening a (root or child) span in this process."""

    def __init__(self, name: str, **attributes):
        self._name = name
        self._attrs = attributes
        self.span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Span:
        parent = _current.get()
        trace_id = parent[0] if parent else _new_id()
        self.span = Span(trace_id=trace_id, span_id=_new_id(),
                         parent_span_id=parent[1] if parent else None,
                         name=self._name, attributes=dict(self._attrs))
        self._token = _current.set((trace_id, self.span.span_id))
        return self.span

    def __exit__(self, *exc) -> None:
        _current.reset(self._token)
        self.span.end = time.time()
        _record(self.span)


def current_context() -> Optional[tuple]:
    """(trace_id, span_id) to stamp onto outgoing TaskSpecs, or None."""
    return _current.get()


def activate(ctx: Optional[tuple]):
    """Worker-side: re-activate the submitter's context around a task
    (returns the reset token)."""
    return _current.set(tuple(ctx) if ctx else None)


def deactivate(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def task_span(spec):
    """Worker-side wrapper for one task execution: re-activate the
    submitter's context, open the task's span, and ALWAYS reset the
    thread's context afterwards — worker threads are long-lived, and a
    leaked contextvar would stamp every later (even untraced) task on
    this thread into the wrong trace."""
    if not getattr(spec, "trace_ctx", None):
        yield None
        return
    token = activate(spec.trace_ctx)
    try:
        with trace(spec.description, task_id=spec.task_id.hex()) as span:
            yield span
    finally:
        deactivate(token)


def _record(span: Span) -> None:
    """Spans land in the GCS task-event stream (local or via channel)."""
    event = {
        # the task id (when this span wraps a task) joins span events to
        # the task's RUNNING/FINISHED events in the same stream
        "task_id": span.attributes.get("task_id", ""),
        "name": span.name, "state": "SPAN",
        "trace_id": span.trace_id, "span_id": span.span_id,
        "parent_span_id": span.parent_span_id,
        "time": span.start, "end_time": span.end,
        "attributes": span.attributes,
        # provenance: timeline() groups span slices into per-process
        # lanes and draws cross-process flow arrows from these
        "pid": os.getpid(),
    }
    try:
        from ..core import runtime as runtime_mod

        rt = runtime_mod.maybe_runtime()
    except Exception:
        rt = None
    if rt is not None:
        node = getattr(getattr(rt, "worker", None), "node_id_hex", None)
        event["node_id"] = node or ("head" if hasattr(rt, "gcs") else "")
    if span_export is not None:
        try:
            span_export(event)
        except Exception as e:  # noqa: BLE001 — counted, warned once
            _note_span_drop("exporter", e)
    try:
        if rt is None:
            return
        if hasattr(rt, "gcs"):
            rt.gcs.add_task_event(event)
        else:  # worker/client: ship to the head
            rt.channel.notify("log_event", event)
    except Exception as e:  # noqa: BLE001 — counted, warned once
        _note_span_drop("ship", e)


def get_trace(trace_id: str) -> List[dict]:
    """All recorded spans (and traced task events) of one trace, ordered
    by start time — the call tree via parent_span_id links."""
    from ..core import runtime as runtime_mod

    rt = runtime_mod.get_runtime()
    events = (rt.gcs.task_events() if hasattr(rt, "gcs")
              else rt.channel.call("task_events", {}))
    out = [dict(e) for e in events if e.get("trace_id") == trace_id]
    out.sort(key=lambda e: e.get("time", 0.0))
    return out
