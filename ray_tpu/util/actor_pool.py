"""ActorPool — fan a stream of work over a fixed set of actors.

API parity with `ray.util.ActorPool` (ref: python/ray/util/actor_pool.py
public surface: submit/get_next/get_next_unordered/map/map_unordered/
has_next/has_free/push/pop_idle). The implementation is this repo's own:
work is ticketed in submission order, a FIFO backlog feeds freed actors,
and ordered retrieval walks the ticket sequence while unordered
retrieval leans on `ray_tpu.wait`.
"""
from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Iterable, Iterator, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._free = collections.deque(actors)
        self._backlog: collections.deque = collections.deque()
        self._inflight: dict = {}       # ref -> (ticket, actor)
        self._by_ticket: dict = {}      # ticket -> ref
        self._tickets = itertools.count()
        self._head = 0                  # oldest ticket not yet returned

    # -- submission ----------------------------------------------------------

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; parks in the backlog when every
        actor is busy."""
        if self._free:
            self._launch(fn, value)
        else:
            self._backlog.append((fn, value))

    def _launch(self, fn: Callable, value: Any) -> None:
        actor = self._free.popleft()
        ref = fn(actor, value)
        ticket = next(self._tickets)
        self._inflight[ref] = (ticket, actor)
        self._by_ticket[ticket] = ref

    def _recycle(self, actor: Any) -> None:
        self._free.append(actor)
        while self._backlog and self._free:
            self._launch(*self._backlog.popleft())

    # -- retrieval -----------------------------------------------------------

    def has_next(self) -> bool:
        return bool(self._inflight)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order. A timeout leaves the pool
        state untouched so the call can simply be retried."""
        if not self._inflight:
            raise StopIteration("No more results to get")
        head = self._head
        while head not in self._by_ticket:
            head += 1  # that ticket was consumed unordered; skip
        ref = self._by_ticket[head]
        value = ray_tpu.get(ref, timeout=timeout)  # may raise: state intact
        del self._by_ticket[head]
        self._head = head + 1
        _, actor = self._inflight.pop(ref)
        self._recycle(actor)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in COMPLETION order."""
        if not self._inflight:
            raise StopIteration("No more results to get")
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        ref = ready[0]
        ticket, actor = self._inflight.pop(ref)
        self._by_ticket.pop(ticket, None)
        self._recycle(actor)
        return ray_tpu.get(ref)

    # -- bulk ----------------------------------------------------------------

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- membership ----------------------------------------------------------

    def push(self, actor: Any) -> None:
        self._recycle(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._free.pop() if self._free else None

    def has_free(self) -> bool:
        return bool(self._free) and not self._backlog
