"""ActorPool — fan work over a fixed set of actors.

Parity with the reference's `ray.util.ActorPool`
(ref: python/ray/util/actor_pool.py — submit/get_next/get_next_unordered,
map/map_unordered over idle actors, push/pop for membership)."""
from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        self._future_to_actor: dict = {}
        self._pending_submits: List[tuple] = []
        self._next_task_index = 0
        self._index_to_future: dict = {}
        self._next_return_index = 0

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued if no actor is idle
        (ref: actor_pool.py:81)."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def _return_actor(self, actor: Any) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    # -- retrieval ---------------------------------------------------------

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in SUBMISSION order (ref: actor_pool.py:150)."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        ref = self._index_to_future[self._next_return_index]
        result = ray_tpu.get(ref, timeout=timeout)
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(ref)
        self._return_actor(actor)
        return result

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result in COMPLETION order (ref: actor_pool.py:188)."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        ref = ready[0]
        idx, actor = self._future_to_actor.pop(ref)
        self._index_to_future.pop(idx, None)
        self._return_actor(actor)
        return ray_tpu.get(ref)

    # -- bulk --------------------------------------------------------------

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- membership --------------------------------------------------------

    def push(self, actor: Any) -> None:
        self._return_actor(actor)

    def pop_idle(self) -> Any:
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits
