"""ray_tpu.workflow — durable workflows (checkpointed task DAGs).

Equivalent of the reference's ray.workflow (ref: python/ray/workflow/ —
api.py run/resume, workflow_storage.py step-result persistence,
workflow_state_from_storage.py resume). A workflow is a DAG of steps;
each step runs as a regular task and its result is checkpointed to
durable storage before dependents see it, so a crashed driver resumes
from the last completed step instead of re-running the graph.

    @workflow.step
    def fetch(url): ...

    @workflow.step
    def merge(a, b): ...

    dag = merge.step(fetch.step("u1"), fetch.step("u2"))
    result = workflow.run(dag, workflow_id="ingest-2026-07-30")
    # crash anywhere -> workflow.resume("ingest-2026-07-30")
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu

# statuses (ref: workflow/common.py WorkflowStatus)
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
RESUMABLE = "RESUMABLE"


def _storage_root() -> str:
    return os.environ.get("RTPU_WORKFLOW_STORAGE",
                          os.path.expanduser("~/ray_tpu_workflows"))


@dataclass
class StepNode:
    """One node of the DAG; args may contain other StepNodes."""
    fn_blob: bytes
    name: str
    args: tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    num_cpus: float = 1.0
    max_retries: int = 3

    def step_key(self, position: str) -> str:
        """Stable identity: DAG position + code identity + literal-input
        identity — a changed function OR changed inputs invalidates the
        old checkpoint (content addressing the reference gets from step
        ids). Child StepNodes are replaced by position markers: their own
        keys already capture their content."""
        def enc(v):
            if isinstance(v, StepNode):
                return b"<step>"
            try:
                return cloudpickle.dumps(v)
            except Exception:
                return repr(v).encode()

        h = hashlib.sha1(self.fn_blob)
        for a in self.args:
            h.update(enc(a))
        for k in sorted(self.kwargs):
            h.update(k.encode())
            h.update(enc(self.kwargs[k]))
        return f"{position}_{self.name}_{h.hexdigest()[:12]}"


class _StepFunction:
    def __init__(self, fn: Callable, num_cpus: float = 1.0,
                 max_retries: int = 3):
        self._fn = fn
        self._blob = cloudpickle.dumps(fn)
        self._name = getattr(fn, "__name__", "step")
        self._num_cpus = num_cpus
        self._max_retries = max_retries

    def step(self, *args, **kwargs) -> StepNode:
        return StepNode(self._blob, self._name, args, kwargs,
                        self._num_cpus, self._max_retries)

    def options(self, *, num_cpus: float = None,
                max_retries: int = None) -> "_StepFunction":
        return _StepFunction(
            self._fn,
            self._num_cpus if num_cpus is None else num_cpus,
            self._max_retries if max_retries is None else max_retries)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)  # direct local call still works


def step(fn: Callable = None, **opts) -> _StepFunction:
    """Decorator marking a function as a workflow step."""
    if fn is None:
        return lambda f: _StepFunction(f, **opts)
    return _StepFunction(fn)


class _Storage:
    """Filesystem-backed step-result store (ref: workflow_storage.py;
    any shared filesystem gives cross-host durability)."""

    def __init__(self, workflow_id: str):
        self.dir = os.path.join(_storage_root(), workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, "steps", key + ".pkl")

    def has(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def load(self, key: str) -> Any:
        with open(self._path(key), "rb") as f:
            return cloudpickle.load(f)

    def save(self, key: str, value: Any) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._path(key))  # atomic: no torn checkpoints

    def meta(self) -> dict:
        p = os.path.join(self.dir, "workflow.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def set_meta(self, **kw) -> None:
        m = self.meta()
        m.update(kw)
        tmp = os.path.join(self.dir, "workflow.json.tmp")
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, os.path.join(self.dir, "workflow.json"))


def _submit(node: StepNode, storage: _Storage, position: str,
            pending: List[tuple]):
    """Submit the whole subtree WITHOUT blocking: child results travel as
    ObjectRefs straight into the parent's arguments, so independent
    branches run concurrently across the cluster (a serial tree walk
    would strand an N-way fan-out at 1x parallelism). Returns the ref of
    this node's result; `pending` collects (key, ref, cached) post-order
    for the checkpointing pass."""
    key = node.step_key(position)
    if storage.has(key):
        ref = ray_tpu.put(storage.load(key))  # replay from checkpoint
        pending.append((key, ref, True))
        return ref
    args = [(_submit(a, storage, f"{position}.{i}", pending)
             if isinstance(a, StepNode) else a)
            for i, a in enumerate(node.args)]
    kwargs = {k: (_submit(v, storage, f"{position}.{k}", pending)
                  if isinstance(v, StepNode) else v)
              for k, v in node.kwargs.items()}
    fn = cloudpickle.loads(node.fn_blob)
    ref = ray_tpu.remote(fn).options(
        num_cpus=node.num_cpus,
        max_retries=node.max_retries).remote(*args, **kwargs)
    pending.append((key, ref, False))
    return ref


def _execute(node: StepNode, storage: _Storage, position: str) -> Any:
    pending: List[tuple] = []
    root_ref = _submit(node, storage, position, pending)
    # checkpoint in post-order (children land before parents); a crash
    # mid-graph loses only steps whose results hadn't arrived yet
    result = None
    for key, ref, cached in pending:
        result = ray_tpu.get(ref)
        if not cached:
            storage.save(key, result)
    # the root is the last post-order entry
    assert pending[-1][1] is root_ref
    return result


def run(dag: StepNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute the DAG durably; returns the root step's result.
    Re-running with the same workflow_id resumes (completed steps are
    read from storage, not re-executed)."""
    if not isinstance(dag, StepNode):
        raise TypeError("workflow.run expects a StepNode "
                        "(build one with @workflow.step + .step(...))")
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000)}"
    storage = _Storage(workflow_id)
    storage.set_meta(status=RUNNING, started_at=time.time(),
                     dag_blob_sha=hashlib.sha1(dag.fn_blob).hexdigest())
    # persist the DAG itself so resume() works without the user's code
    with open(os.path.join(storage.dir, "dag.pkl"), "wb") as f:
        cloudpickle.dump(dag, f)
    try:
        result = _execute(dag, storage, "root")
    except BaseException as e:
        storage.set_meta(status=RESUMABLE, error=repr(e),
                         failed_at=time.time())
        raise
    storage.set_meta(status=SUCCESSFUL, finished_at=time.time())
    return result


def resume(workflow_id: str) -> Any:
    """Continue an interrupted workflow from its checkpoints (ref:
    api.py resume)."""
    storage = _Storage(workflow_id)
    dag_path = os.path.join(storage.dir, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    with open(dag_path, "rb") as f:
        dag = cloudpickle.load(f)
    storage.set_meta(status=RUNNING, resumed_at=time.time())
    try:
        result = _execute(dag, storage, "root")
    except BaseException as e:
        storage.set_meta(status=RESUMABLE, error=repr(e),
                         failed_at=time.time())
        raise
    storage.set_meta(status=SUCCESSFUL, finished_at=time.time())
    return result


def get_status(workflow_id: str) -> Optional[str]:
    return _Storage(workflow_id).meta().get("status")


def list_all(status_filter: Optional[str] = None) -> List[tuple]:
    root = _storage_root()
    if not os.path.isdir(root):
        return []
    out = []
    for wid in sorted(os.listdir(root)):
        st = _Storage(wid).meta().get("status")
        if st and (status_filter is None or st == status_filter):
            out.append((wid, st))
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(_storage_root(), workflow_id),
                  ignore_errors=True)
