"""ray_tpu.workflow — durable workflows (checkpointed task DAGs).

Equivalent of the reference's ray.workflow (ref: python/ray/workflow/ —
api.py run/resume, workflow_storage.py step-result persistence,
workflow_state_from_storage.py resume). A workflow is a DAG of steps;
each step runs as a regular task and its result is checkpointed to
durable storage before dependents see it, so a crashed driver resumes
from the last completed step instead of re-running the graph.

    @workflow.step
    def fetch(url): ...

    @workflow.step
    def merge(a, b): ...

    dag = merge.step(fetch.step("u1"), fetch.step("u2"))
    result = workflow.run(dag, workflow_id="ingest-2026-07-30")
    # crash anywhere -> workflow.resume("ingest-2026-07-30")
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu

# statuses (ref: workflow/common.py WorkflowStatus)
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
RESUMABLE = "RESUMABLE"


def _storage_root() -> str:
    return os.environ.get("RTPU_WORKFLOW_STORAGE",
                          os.path.expanduser("~/ray_tpu_workflows"))


@dataclass
class StepNode:
    """One node of the DAG; args may contain other StepNodes."""
    fn_blob: bytes
    name: str
    args: tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    num_cpus: float = 1.0
    max_retries: int = 3

    def step_key(self, position: str) -> str:
        """Stable identity: DAG position + code identity + literal-input
        identity — a changed function OR changed inputs invalidates the
        old checkpoint (content addressing the reference gets from step
        ids). Child StepNodes are replaced by position markers: their own
        keys already capture their content."""
        def enc(v):
            if isinstance(v, StepNode):
                return b"<step>"
            try:
                return cloudpickle.dumps(v)
            except Exception:
                return repr(v).encode()

        h = hashlib.sha1(self.fn_blob)
        for a in self.args:
            h.update(enc(a))
        for k in sorted(self.kwargs):
            h.update(k.encode())
            h.update(enc(self.kwargs[k]))
        return f"{position}_{self.name}_{h.hexdigest()[:12]}"


class _StepFunction:
    def __init__(self, fn: Callable, num_cpus: float = 1.0,
                 max_retries: int = 3):
        self._fn = fn
        # serialization is DEFERRED to the first .step() call: pickling at
        # decoration time would capture an empty closure cell for
        # recursive steps (`fact` isn't bound until the decorator
        # returns), breaking dynamic-continuation recursion
        self._blob: Optional[bytes] = None
        self._name = getattr(fn, "__name__", "step")
        self._num_cpus = num_cpus
        self._max_retries = max_retries

    def step(self, *args, **kwargs) -> StepNode:
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._fn)
        return StepNode(self._blob, self._name, args, kwargs,
                        self._num_cpus, self._max_retries)

    def options(self, *, num_cpus: float = None,
                max_retries: int = None) -> "_StepFunction":
        return _StepFunction(
            self._fn,
            self._num_cpus if num_cpus is None else num_cpus,
            self._max_retries if max_retries is None else max_retries)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)  # direct local call still works


def step(fn: Callable = None, **opts) -> _StepFunction:
    """Decorator marking a function as a workflow step."""
    if fn is None:
        return lambda f: _StepFunction(f, **opts)
    return _StepFunction(fn)


class _Storage:
    """Filesystem-backed step-result store (ref: workflow_storage.py;
    any shared filesystem gives cross-host durability)."""

    def __init__(self, workflow_id: str):
        self.dir = os.path.join(_storage_root(), workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, "steps", key + ".pkl")

    def has(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def load(self, key: str) -> Any:
        with open(self._path(key), "rb") as f:
            return cloudpickle.load(f)

    def save(self, key: str, value: Any) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._path(key))  # atomic: no torn checkpoints

    def meta(self) -> dict:
        p = os.path.join(self.dir, "workflow.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def set_meta(self, **kw) -> None:
        m = self.meta()
        m.update(kw)
        tmp = os.path.join(self.dir, "workflow.json.tmp")
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, os.path.join(self.dir, "workflow.json"))


@dataclass
class EventNode:
    """A durable wait point (ref: workflow/api.py wait_for_event +
    workflow/event_listener.py). Execution blocks until the named event
    is delivered — via `workflow.deliver_event` (the built-in
    storage-backed listener) or a custom `listener()` callable returning
    the payload (or None to keep waiting). The received payload
    checkpoints like any step result, so a resumed workflow does NOT
    re-wait for an event it already saw."""
    name: str
    timeout_s: Optional[float] = None
    listener_blob: Optional[bytes] = None
    poll_interval_s: float = 0.2

    def step_key(self, position: str) -> str:
        return f"{position}_event_{self.name}"


def wait_for_event(name: str, *, timeout_s: Optional[float] = None,
                   listener: Optional[Callable[[], Any]] = None,
                   poll_interval_s: float = 0.2) -> EventNode:
    """A DAG node that resolves when the event arrives; use it as an
    argument to any step."""
    return EventNode(name, timeout_s,
                     cloudpickle.dumps(listener) if listener else None,
                     poll_interval_s)


def deliver_event(workflow_id: str, name: str, payload: Any = None) -> None:
    """Deliver an event to a (possibly currently waiting) workflow."""
    storage = _Storage(workflow_id)
    os.makedirs(os.path.join(storage.dir, "events"), exist_ok=True)
    path = os.path.join(storage.dir, "events", name + ".pkl")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        cloudpickle.dump(payload, f)
    os.replace(tmp, path)


@dataclass
class _Continuation:
    """Durable marker persisted under a hop's key when the step RETURNED
    another StepNode: resume loads it and re-enters the chain at that
    hop instead of re-running everything before it."""
    node: Any


class _Executor:
    """Driver-side scheduler: every child subtree resolves on its own
    thread (parallel fan-out), each step's value checkpoints before
    parents consume it, and a step that RETURNS a StepNode is a dynamic
    continuation (ref: workflow continuation semantics). Continuations
    run as an ITERATIVE trampoline — each hop persists a _Continuation
    marker, so arbitrarily long chains neither blow the Python stack nor
    lose progress on a crash."""

    MAX_CONTINUATIONS = 100_000  # runaway-loop backstop

    MAX_RESOLVER_THREADS = 64

    def __init__(self, storage: _Storage):
        import threading

        self.storage = storage
        # a failed sibling aborts event waits so a co-scheduled
        # wait_for_event with no timeout can't hang the whole run
        self._abort = threading.Event()
        # bounds concurrent resolver threads across the whole run. A
        # child that can't get a permit resolves INLINE on its parent's
        # thread (never blocks on the semaphore), so wide/deep DAGs
        # degrade to partial serialization instead of thread exhaustion
        # or a nested-pool deadlock.
        self._thread_permits = threading.Semaphore(
            self.MAX_RESOLVER_THREADS)

    def execute(self, node, position: str) -> Any:
        value, _ref = self._resolve(node, position)
        return value

    def _resolve(self, node, position: str):
        """-> (value, task_ref_or_None). The ref, when present, lets a
        parent pass the result WITHOUT re-uploading it (the child task's
        store copy is reused)."""
        if isinstance(node, EventNode):
            return self._await_event(node, position), None
        root_key = node.step_key(position)
        cur, curpos, hops = node, position, 0
        ref = None
        while True:
            if isinstance(cur, EventNode):
                value = self._await_event(cur, curpos)
                ref = None
            else:
                key = cur.step_key(curpos)
                if self.storage.has(key):
                    value = self.storage.load(key)
                    ref = None
                else:
                    value, ref = self._run_step(cur, curpos)
                    self.storage.save(
                        key, _Continuation(value)
                        if isinstance(value, (StepNode, EventNode))
                        else value)
            if isinstance(value, _Continuation):
                value = value.node  # loaded marker: re-enter the chain
            if not isinstance(value, (StepNode, EventNode)):
                break
            hops += 1
            if hops > self.MAX_CONTINUATIONS:
                raise RuntimeError(
                    f"step {root_key} exceeded {self.MAX_CONTINUATIONS} "
                    "continuations (infinite loop?)")
            cur, curpos = value, f"{position}.c{hops}"
        if hops:
            # the chain's final value also lands under the ROOT key so a
            # completed chain replays in one load
            self.storage.save(root_key, value)
        return value, ref

    def _run_step(self, node: StepNode, position: str):
        import threading

        results: Dict[Any, Any] = {}
        errors: List[BaseException] = []

        def resolve(slot, child, child_pos):
            try:
                value, child_ref = self._resolve(child, child_pos)
                # hand the parent the child task's existing store copy
                # when there is one — re-inlining a multi-GB value would
                # round-trip it through driver memory a second time
                results[slot] = child_ref if child_ref is not None else value
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
                self._abort.set()

        def resolve_permitted(slot, child, child_pos):
            try:
                resolve(slot, child, child_pos)
            finally:
                self._thread_permits.release()

        pending = []
        for i, a in enumerate(node.args):
            if isinstance(a, (StepNode, EventNode)):
                pending.append((i, a, f"{position}.{i}"))
            else:
                results[i] = a
        for k, v in node.kwargs.items():
            if isinstance(v, (StepNode, EventNode)):
                pending.append((k, v, f"{position}.{k}"))
            else:
                results[k] = v
        threads = []
        inline = []
        step_children = [p for p in pending
                         if not isinstance(p[1], EventNode)]
        for idx, item in enumerate(pending):
            if isinstance(item[1], EventNode):
                # event waits ALWAYS get their own (unpermitted) thread: a
                # wait parked inline or holding a permit for its whole
                # (possibly unbounded) duration would serialize against —
                # or starve — the sibling steps that trigger the event
                t = threading.Thread(target=resolve, args=item, daemon=True)
                threads.append(t)
                t.start()
            elif item is not step_children[-1] \
                    and self._thread_permits.acquire(blocking=False):
                t = threading.Thread(target=resolve_permitted, args=item,
                                     daemon=True)
                threads.append(t)
                t.start()
            else:
                # no permit (or last step child): run on this thread —
                # at least one child makes progress without a new thread
                inline.append(item)
        for item in inline:
            resolve(*item)
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        args = [results[i] for i in range(len(node.args))]
        kwargs = {k: results[k] for k in node.kwargs}
        fn = cloudpickle.loads(node.fn_blob)
        ref = ray_tpu.remote(fn).options(
            num_cpus=node.num_cpus,
            max_retries=node.max_retries).remote(*args, **kwargs)
        return ray_tpu.get(ref), ref

    def _await_event(self, node: EventNode, position: str) -> Any:
        key = node.step_key(position)
        if self.storage.has(key):
            return self.storage.load(key)  # already received pre-crash
        listener = (cloudpickle.loads(node.listener_blob)
                    if node.listener_blob else None)
        path = os.path.join(self.storage.dir, "events", node.name + ".pkl")
        deadline = (time.monotonic() + node.timeout_s
                    if node.timeout_s is not None else None)
        while True:
            if self._abort.is_set():
                raise RuntimeError(
                    f"event wait {node.name!r} aborted: a sibling step "
                    "failed")
            if listener is not None:
                payload = listener()
                if payload is not None:
                    break
            elif os.path.exists(path):
                with open(path, "rb") as f:
                    payload = cloudpickle.load(f)
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"event {node.name!r} not delivered within "
                    f"{node.timeout_s}s")
            time.sleep(node.poll_interval_s)
        self.storage.save(key, payload)
        return payload


def _execute(node: StepNode, storage: _Storage, position: str) -> Any:
    return _Executor(storage).execute(node, position)


def run(dag: StepNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute the DAG durably; returns the root step's result.
    Re-running with the same workflow_id resumes (completed steps are
    read from storage, not re-executed)."""
    if not isinstance(dag, StepNode):
        raise TypeError("workflow.run expects a StepNode "
                        "(build one with @workflow.step + .step(...))")
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000)}"
    storage = _Storage(workflow_id)
    storage.set_meta(status=RUNNING, started_at=time.time(),
                     dag_blob_sha=hashlib.sha1(dag.fn_blob).hexdigest())
    # persist the DAG itself so resume() works without the user's code
    with open(os.path.join(storage.dir, "dag.pkl"), "wb") as f:
        cloudpickle.dump(dag, f)
    try:
        result = _execute(dag, storage, "root")
    except BaseException as e:
        storage.set_meta(status=RESUMABLE, error=repr(e),
                         failed_at=time.time())
        raise
    storage.set_meta(status=SUCCESSFUL, finished_at=time.time())
    return result


def resume(workflow_id: str) -> Any:
    """Continue an interrupted workflow from its checkpoints (ref:
    api.py resume)."""
    storage = _Storage(workflow_id)
    dag_path = os.path.join(storage.dir, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    with open(dag_path, "rb") as f:
        dag = cloudpickle.load(f)
    storage.set_meta(status=RUNNING, resumed_at=time.time())
    try:
        result = _execute(dag, storage, "root")
    except BaseException as e:
        storage.set_meta(status=RESUMABLE, error=repr(e),
                         failed_at=time.time())
        raise
    storage.set_meta(status=SUCCESSFUL, finished_at=time.time())
    return result


def get_status(workflow_id: str) -> Optional[str]:
    return _Storage(workflow_id).meta().get("status")


def list_all(status_filter: Optional[str] = None) -> List[tuple]:
    root = _storage_root()
    if not os.path.isdir(root):
        return []
    out = []
    for wid in sorted(os.listdir(root)):
        st = _Storage(wid).meta().get("status")
        if st and (status_filter is None or st == status_filter):
            out.append((wid, st))
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(_storage_root(), workflow_id),
                  ignore_errors=True)
