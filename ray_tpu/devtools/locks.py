"""Debug-mode lock instrumentation for the multi-threaded core runtime.

The reference ships whole C++ subsystems for this hazard class (TSAN
wiring, ABSL lock annotations, ``debug/lock_debug.h``); a pure-Python
runtime gets no compiler help, so this module provides the runtime half
of graftcheck: an ``instrumented_lock()`` factory the core's hot locks
are built from.

With ``RAY_TPU_DEBUG_LOCKS`` unset (the default) the factory returns a
plain ``threading.Lock``/``RLock`` — zero overhead on the hot path. With
``RAY_TPU_DEBUG_LOCKS=1`` it returns an :class:`InstrumentedLock` that

- records, per thread, the stack of currently-held instrumented locks
  and the call site of each acquisition;
- maintains a global acquired-while-holding order graph between lock
  *roles* (the names passed to ``instrumented_lock``) and reports a
  **lock-order inversion** the first time an acquisition closes a cycle
  in that graph (the classic AB/BA deadlock precondition — reported with
  both acquisition stacks, without needing the deadlock to strike);
- reports **long holds**: a lock held longer than
  ``RAY_TPU_LOCK_HOLD_WARN_S`` seconds (default 1.0) — a latency smell in
  a runtime whose scheduler and object directory sit behind these locks.

Reports flow through the existing observability path: they are appended
to a bounded in-process buffer (``get_lock_reports()``), logged via the
``ray_tpu.devtools.locks`` logger, and — when a runtime is up — pushed
into the GCS task-event stream, where they surface in the dashboard
timeline and as ``ray_tpu_task_events_total{state="LOCK_..."}`` in
/metrics.
"""
from __future__ import annotations

import atexit
import collections
import logging
import os
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Set, Tuple

logger = logging.getLogger("ray_tpu.devtools.locks")

_TRUTHY = ("1", "true", "on", "yes")


def debug_locks_enabled() -> bool:
    return os.environ.get("RAY_TPU_DEBUG_LOCKS", "").lower() in _TRUTHY


def _hold_warn_threshold() -> float:
    try:
        return float(os.environ.get("RAY_TPU_LOCK_HOLD_WARN_S", "1.0"))
    except ValueError:
        return 1.0


@dataclass
class LockReport:
    """One detected hazard (inversion or long hold)."""

    kind: str  # "lock-order-inversion" | "long-hold"
    message: str
    thread: str
    locks: Tuple[str, ...]
    stacks: Dict[str, str] = field(default_factory=dict)
    time: float = field(default_factory=time.time)

    def as_dict(self) -> dict:
        return {"kind": self.kind, "message": self.message,
                "thread": self.thread, "locks": list(self.locks),
                "stacks": dict(self.stacks), "time": self.time}


class _Registry:
    """Process-global detector state (order graph + report buffer).

    A single plain Lock guards everything; instrumented locks never call
    back into the registry while holding it, so the registry lock cannot
    itself participate in an inversion.
    """

    def __init__(self):
        self._mu = threading.Lock()
        # role -> roles acquired while holding it (order graph edges)
        self._edges: Dict[str, Set[str]] = collections.defaultdict(set)
        # (held_role, acquired_role) -> acquisition stack that created it
        self._edge_sites: Dict[Tuple[str, str], str] = {}
        self._reported_cycles: Set[frozenset] = set()
        self.reports: Deque[LockReport] = collections.deque(maxlen=256)
        # GCS publications deferred while the reporting thread still holds
        # instrumented locks (publishing acquires the instrumented GCS
        # lock — doing that from inside a critical section would extend
        # the hold being diagnosed and inject instrumentation edges into
        # the order graph)
        self._pending_gcs: Deque[LockReport] = collections.deque(maxlen=256)
        self._tls = threading.local()

    # ---- per-thread held-lock stack -------------------------------------

    def held_stack(self) -> List[dict]:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    # ---- order graph ----------------------------------------------------

    def _path_exists(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS: a src -> ... -> dst chain in the order graph, if any."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            cur, path = stack.pop()
            for nxt in self._edges.get(cur, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_acquisition(self, role: str, stack_str: str,
                         held: List[dict]) -> None:
        """Record edges held-role -> role; report on closing a cycle."""
        report: Optional[LockReport] = None
        with self._mu:
            for h in held:
                hrole = h["role"]
                if hrole == role:
                    continue  # same role (reentrant or sibling instance)
                cycle = self._path_exists(role, hrole)
                new_edge = role not in self._edges[hrole]
                if new_edge:
                    self._edges[hrole].add(role)
                    self._edge_sites[(hrole, role)] = stack_str
                if cycle is not None:
                    key = frozenset(cycle) | {role}
                    if key in self._reported_cycles:
                        continue
                    self._reported_cycles.add(key)
                    chain = " -> ".join(cycle + [role])
                    prior = self._edge_sites.get((cycle[0], cycle[1])
                                                 if len(cycle) > 1 else
                                                 (hrole, role), "")
                    report = LockReport(
                        kind="lock-order-inversion",
                        message=(f"lock-order inversion: acquiring '{role}' "
                                 f"while holding '{hrole}' closes the cycle "
                                 f"{chain} (opposite order seen earlier)"),
                        thread=threading.current_thread().name,
                        locks=tuple(cycle + [role]),
                        stacks={"this_acquisition": stack_str,
                                "holding_site": h.get("stack", ""),
                                "prior_order_site": prior},
                    )
        if report is not None:
            self._emit(report)

    def note_long_hold(self, role: str, held_for: float,
                       stack_str: str) -> None:
        report = LockReport(
            kind="long-hold",
            message=(f"lock '{role}' held for {held_for:.3f}s "
                     f"(threshold {_hold_warn_threshold():.3f}s)"),
            thread=threading.current_thread().name,
            locks=(role,),
            stacks={"acquisition": stack_str},
        )
        self._emit(report)

    # ---- reporting ------------------------------------------------------

    def _emit(self, report: LockReport) -> None:
        with self._mu:
            self.reports.append(report)
        logger.warning("%s [thread=%s]", report.message, report.thread)
        if self.held_stack():
            # inside a critical section: defer the GCS write (it acquires
            # the instrumented GCS lock) until this thread drops its last
            # instrumented lock
            with self._mu:
                self._pending_gcs.append(report)
        else:
            self._publish_gcs(report)

    def flush_pending_gcs(self) -> None:
        """Publish reports deferred while their thread held locks."""
        while True:
            with self._mu:
                if not self._pending_gcs:
                    return
                report = self._pending_gcs.popleft()
            self._publish_gcs(report)

    def _publish_gcs(self, report: LockReport) -> None:
        # observability path: ride the GCS task-event stream so the hazard
        # shows up in the dashboard timeline and /metrics event counters
        try:
            from ..core import runtime as _runtime_mod

            rt = _runtime_mod.maybe_runtime()
            gcs = getattr(rt, "gcs", None)
            if gcs is not None:
                gcs.add_task_event({
                    "task_id": "",
                    "name": report.message,
                    "state": ("LOCK_INVERSION"
                              if report.kind == "lock-order-inversion"
                              else "LOCK_LONG_HOLD"),
                    "time": report.time,
                })
        except Exception:
            pass

    def snapshot(self) -> List[LockReport]:
        with self._mu:
            return list(self.reports)

    def order_edges(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted((held, acq)
                          for held, acqs in self._edges.items()
                          for acq in acqs)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._edge_sites.clear()
            self._reported_cycles.clear()
            self.reports.clear()
            self._pending_gcs.clear()
        self._tls = threading.local()


_registry = _Registry()


def get_lock_reports() -> List[LockReport]:
    """All hazards detected so far in this process (bounded buffer)."""
    return _registry.snapshot()


def reset_lock_state() -> None:
    """Clear the order graph and report buffer (test isolation)."""
    _registry.reset()


def get_order_edges() -> List[Tuple[str, str]]:
    """The observed role-level order graph as (held, acquired) edges.

    This is the dynamic twin of graftcheck's static lock-order graph
    (``graftcheck locks``); ``scripts/locks_gate.py`` asserts every edge
    observed here is predicted by the static graph.
    """
    return _registry.order_edges()


def _dump_order_edges() -> None:
    """atexit hook: append observed edges to RAY_TPU_LOCK_ORDER_DUMP.

    Runs in every process (workers included — they import this module
    when building their locks), so the gate sees the union of edges
    across the whole process tree. O_APPEND keeps concurrent writers
    from interleaving mid-line.
    """
    path = os.environ.get("RAY_TPU_LOCK_ORDER_DUMP", "")
    if not path:
        return
    edges = _registry.order_edges()
    if not edges:
        return
    payload = "".join(f"{held} -> {acq}\n" for held, acq in edges)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, payload.encode())
        finally:
            os.close(fd)
    except OSError:
        pass


if os.environ.get("RAY_TPU_LOCK_ORDER_DUMP"):
    atexit.register(_dump_order_edges)


def _capture_stack(skip: int = 2, limit: int = 8) -> str:
    frames = traceback.extract_stack(limit=limit + skip)[:-skip]
    return "".join(traceback.format_list(frames))


class InstrumentedLock:
    """Drop-in Lock/RLock replacement that feeds the hazard detectors.

    Only constructed when ``RAY_TPU_DEBUG_LOCKS`` is set; the factory
    below hands back raw ``threading`` locks otherwise.
    """

    __slots__ = ("_role", "_lock", "_reentrant")

    def __init__(self, role: str, reentrant: bool = False):
        self._role = role
        self._reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()

    @property
    def role(self) -> str:
        return self._role

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # the wrapper IS the lock: release flows through self.release()
        # graftcheck: disable=GC006,GC030
        got = self._lock.acquire(blocking, timeout)
        if not got:
            return False
        held = _registry.held_stack()
        me = id(self)
        for h in held:
            if h["instance"] == me:
                h["count"] += 1  # reentrant re-acquire: no new edges
                return True
        stack_str = _capture_stack()
        _registry.note_acquisition(self._role, stack_str, held)
        held.append({"role": self._role, "instance": me, "count": 1,
                     "t0": time.monotonic(), "stack": stack_str})
        return True

    def release(self) -> None:
        held = _registry.held_stack()
        me = id(self)
        entry = None
        for i in range(len(held) - 1, -1, -1):
            if held[i]["instance"] == me:
                held[i]["count"] -= 1
                if held[i]["count"] == 0:
                    entry = held.pop(i)
                break
        # release FIRST: the report path must not run inside (and extend)
        # the critical section it is diagnosing
        self._lock.release()
        if entry is not None:
            dur = time.monotonic() - entry["t0"]
            if dur > _hold_warn_threshold():
                _registry.note_long_hold(self._role, dur, entry["stack"])
        if not held:
            _registry.flush_pending_gcs()

    def locked(self) -> bool:
        if self._reentrant:
            # RLock has no locked(); try-acquire probe
            if self._lock.acquire(blocking=False):
                self._lock.release()
                return False
            return True
        return self._lock.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self._reentrant else "Lock"
        return f"<InstrumentedLock {kind} role={self._role!r}>"


def instrumented_lock(role: str, reentrant: bool = False):
    """Factory for the core runtime's hot locks.

    ``role`` names the lock's job (e.g. ``"runtime.driver"``) — the
    order graph is built between roles, so every instance of a role
    shares one node. Returns a plain ``threading.Lock``/``RLock`` unless
    ``RAY_TPU_DEBUG_LOCKS=1``.
    """
    if not debug_locks_enabled():
        return threading.RLock() if reentrant else threading.Lock()
    return InstrumentedLock(role, reentrant=reentrant)
