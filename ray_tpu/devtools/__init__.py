"""Developer tooling for the ray_tpu core.

Two halves (see docs/GRAFTCHECK.md):

- ``graftcheck`` — a framework-aware static linter (stdlib ``ast``, no
  third-party deps) with rules GC001..GC006 targeting the correctness
  hazards this runtime shares with the reference (blocking get inside
  remote bodies, unserializable closure capture, global mutation from
  tasks, blocking sleeps on the actor event loop, swallowed framework
  errors, leak-prone manual lock handling). Run it as
  ``python -m ray_tpu.devtools.graftcheck [paths]``.

- ``locks`` — a debug-mode instrumented lock (``RAY_TPU_DEBUG_LOCKS=1``)
  that the core runtime's hot locks are built from; it records per-thread
  acquisition stacks and reports lock-order inversions and over-long hold
  times through the observability path.
"""
from __future__ import annotations

from .locks import (LockReport, get_lock_reports, instrumented_lock,
                    reset_lock_state)

__all__ = [
    "instrumented_lock",
    "get_lock_reports",
    "reset_lock_state",
    "LockReport",
]
