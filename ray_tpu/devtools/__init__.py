"""Developer tooling for the ray_tpu core.

Two halves (see docs/GRAFTCHECK.md):

- ``graftcheck`` — a framework-aware whole-program analyzer (stdlib
  ``ast``, no third-party deps): per-file rules GC001..GC008 plus an
  engine that builds a project symbol table and remote call graph
  (content-hash cached) for actor-deadlock wait-cycle detection
  (GC010), interprocedural serialization flow (GC011), and the GC020
  TPU/SPMD series (unbound collective axes, in_specs arity,
  donated-buffer reuse). Run it as
  ``python -m ray_tpu.devtools.graftcheck [paths]`` (``--sarif``,
  ``--baseline``, ``graph`` DOT subcommand).

- ``locks`` — a debug-mode instrumented lock (``RAY_TPU_DEBUG_LOCKS=1``)
  that the core runtime's hot locks are built from; it records per-thread
  acquisition stacks and reports lock-order inversions and over-long hold
  times through the observability path.
"""
from __future__ import annotations

from .locks import (LockReport, get_lock_reports, instrumented_lock,
                    reset_lock_state)

__all__ = [
    "instrumented_lock",
    "get_lock_reports",
    "reset_lock_state",
    "LockReport",
]
