"""Generic forward abstract-interpretation fixpoint over a :mod:`.cfg`
control-flow graph (graftcheck v3).

The framework is domain-agnostic: a *domain* object supplies the
abstract semantics and the engine supplies termination. Domains
implement:

``initial() -> state``
    The state at the function entry.

``transfer(node, state) -> state``
    The node's effect. Must NOT mutate its input (states are shared
    between edges); return a new state when anything changes. Findings
    are typically recorded on the domain itself during transfer —
    the engine guarantees every node's transfer runs at least once per
    distinct in-state, and dedup is the domain's job (states grow
    monotonically, so a site can be visited several times).

``join(a, b) -> state``
    Least upper bound. The engine folds incoming edge states into the
    node's in-state with this; the fixpoint terminates when joins stop
    changing anything, so ``join`` must be monotone w.r.t. ``==``.

``assume(state, label) -> state``
    Applied to a flow edge's *assume* annotation (branch-condition
    refinement, e.g. ``("none", "blocks")`` on the true edge of
    ``if blocks is None:``). Return the input unchanged when the label
    does not help.

``exc_edge(node, state) -> state`` (optional)
    Applied to the PRE-state carried along a node's exception edge —
    the lifecycle domain uses it to tell "the release itself raised"
    (best-effort close, benign) apart from "something before the
    release raised" (the leak path).

Edge semantics (matching :mod:`.cfg`):

- ``flow`` edges propagate the node's POST-state (after ``transfer``),
- ``exc`` edges propagate the node's PRE-state — the statement raised
  before its effect took hold.

``run(cfg, domain)`` returns a :class:`FixpointResult` with the
in-state of every node (by index) plus iteration counts for the
``--stats`` surface.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from .cfg import CFG, EXC

# hard iteration ceiling: |nodes| * height-of-lattice is bounded for the
# lifecycle domain, but a buggy domain must not hang the linter
_MAX_VISITS_PER_NODE = 64


class FixpointResult:
    __slots__ = ("in_states", "iterations", "converged")

    def __init__(self, in_states: Dict[int, Any], iterations: int,
                 converged: bool):
        self.in_states = in_states
        self.iterations = iterations
        self.converged = converged


# ---------------------------------------------------------------------------
# held-lock abstract state (graftcheck v5)
#
# The concurrency rules (GC050-054, :mod:`.rules_concurrency`) all run
# the same MUST-analysis: "which locks does this thread provably hold
# here?". The state is a held multiset (reentrant locks nest, so a bare
# set would go empty one ``with`` too early) plus the bindings of
# try-acquire results (``got = lock.acquire(blocking=False)`` — the
# branch on ``got`` decides heldness, via the CFG's some/none assumes).
# MUST semantics make the join an intersection: a lock only counts as
# held after a merge point when every incoming path holds it — exactly
# the conservative direction for "flag accesses with no lock held"
# (under-claiming held locks can only create false positives on merge
# diamonds, never false negatives, and the rules' exemptions absorb
# the few real diamonds in the tree).


class LockState:
    """Immutable held-lock state: (token -> depth) + try-acquire binds.

    Tokens are opaque strings chosen by the domain (the concurrency
    rules use ``self._lock``-style dotted receivers, alias-resolved).
    Depth is capped so pathological ``while True: lock.acquire()``
    loops cannot grow the lattice unboundedly.
    """

    __slots__ = ("held", "binds")
    _MAX_DEPTH = 3

    def __init__(self, held: tuple = (), binds: frozenset = frozenset()):
        self.held = held      # sorted ((token, depth), ...)
        self.binds = binds    # {(name, token)}

    # -- equality / hashing (the fixpoint compares states) ----------------

    def __eq__(self, other):
        return isinstance(other, LockState) and self.held == other.held \
            and self.binds == other.binds

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash((self.held, self.binds))

    def __repr__(self):   # pragma: no cover - debugging aid
        locks = ",".join(f"{t}x{d}" if d > 1 else t for t, d in self.held)
        return f"<LockState [{locks}]>"

    # -- queries ----------------------------------------------------------

    def tokens(self) -> frozenset:
        return frozenset(t for t, _ in self.held)

    def has(self, token: str) -> bool:
        return any(t == token for t, _ in self.held)

    # -- transfers (all return new states) --------------------------------

    def acquire(self, token: str) -> "LockState":
        out = dict(self.held)
        out[token] = min(out.get(token, 0) + 1, self._MAX_DEPTH)
        return LockState(tuple(sorted(out.items())), self.binds)

    def acquire_if_absent(self, token: str) -> "LockState":
        """Establish heldness without nesting (``locked()`` assertions)."""
        return self if self.has(token) else self.acquire(token)

    def release(self, token: str) -> "LockState":
        out = dict(self.held)
        d = out.get(token, 0)
        if d <= 1:
            out.pop(token, None)
        else:
            out[token] = d - 1
        return LockState(tuple(sorted(out.items())), self.binds)

    def bind(self, name: str, token: str) -> "LockState":
        return LockState(self.held, self.binds | {(name, token)})

    def unbind(self, names) -> "LockState":
        names = set(names)
        if not any(n in names for n, _ in self.binds):
            return self
        return LockState(self.held, frozenset(
            (n, t) for n, t in self.binds if n not in names))

    def bound_token(self, name: str) -> Optional[str]:
        for n, t in self.binds:
            if n == name:
                return t
        return None

    def join(self, other: "LockState") -> "LockState":
        """MUST join: intersection, min depth."""
        if self == other:
            return self
        mine = dict(self.held)
        held = tuple(sorted((t, min(d, mine[t]))
                            for t, d in other.held if t in mine))
        return LockState(held, self.binds & other.binds)

    @classmethod
    def entry(cls, tokens) -> "LockState":
        """State for a helper proven to be entered with locks held."""
        return cls(tuple(sorted((t, 1) for t in set(tokens))))


def run(cfg: CFG, domain) -> FixpointResult:
    in_states: Dict[int, Any] = {cfg.entry: domain.initial()}
    visits: Dict[int, int] = {}
    worklist = deque([cfg.entry])
    queued = {cfg.entry}
    iterations = 0
    converged = True
    exc_edge = getattr(domain, "exc_edge", None)

    while worklist:
        idx = worklist.popleft()
        queued.discard(idx)
        iterations += 1
        visits[idx] = visits.get(idx, 0) + 1
        if visits[idx] > _MAX_VISITS_PER_NODE:
            converged = False
            continue
        pre = in_states[idx]
        node = cfg.nodes[idx]
        post = domain.transfer(node, pre)
        for dst, kind, assume in cfg.succ[idx]:
            if kind == EXC:
                carry = exc_edge(node, pre) if exc_edge is not None else pre
            else:
                carry = post
            if assume is not None:
                carry = domain.assume(carry, assume)
            prev = in_states.get(dst)
            nxt = carry if prev is None else domain.join(prev, carry)
            if prev is None or nxt != prev:
                in_states[dst] = nxt
                if dst not in queued:
                    queued.add(dst)
                    worklist.append(dst)
    return FixpointResult(in_states, iterations, converged)
