"""Generic forward abstract-interpretation fixpoint over a :mod:`.cfg`
control-flow graph (graftcheck v3).

The framework is domain-agnostic: a *domain* object supplies the
abstract semantics and the engine supplies termination. Domains
implement:

``initial() -> state``
    The state at the function entry.

``transfer(node, state) -> state``
    The node's effect. Must NOT mutate its input (states are shared
    between edges); return a new state when anything changes. Findings
    are typically recorded on the domain itself during transfer —
    the engine guarantees every node's transfer runs at least once per
    distinct in-state, and dedup is the domain's job (states grow
    monotonically, so a site can be visited several times).

``join(a, b) -> state``
    Least upper bound. The engine folds incoming edge states into the
    node's in-state with this; the fixpoint terminates when joins stop
    changing anything, so ``join`` must be monotone w.r.t. ``==``.

``assume(state, label) -> state``
    Applied to a flow edge's *assume* annotation (branch-condition
    refinement, e.g. ``("none", "blocks")`` on the true edge of
    ``if blocks is None:``). Return the input unchanged when the label
    does not help.

``exc_edge(node, state) -> state`` (optional)
    Applied to the PRE-state carried along a node's exception edge —
    the lifecycle domain uses it to tell "the release itself raised"
    (best-effort close, benign) apart from "something before the
    release raised" (the leak path).

Edge semantics (matching :mod:`.cfg`):

- ``flow`` edges propagate the node's POST-state (after ``transfer``),
- ``exc`` edges propagate the node's PRE-state — the statement raised
  before its effect took hold.

``run(cfg, domain)`` returns a :class:`FixpointResult` with the
in-state of every node (by index) plus iteration counts for the
``--stats`` surface.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from .cfg import CFG, EXC

# hard iteration ceiling: |nodes| * height-of-lattice is bounded for the
# lifecycle domain, but a buggy domain must not hang the linter
_MAX_VISITS_PER_NODE = 64


class FixpointResult:
    __slots__ = ("in_states", "iterations", "converged")

    def __init__(self, in_states: Dict[int, Any], iterations: int,
                 converged: bool):
        self.in_states = in_states
        self.iterations = iterations
        self.converged = converged


def run(cfg: CFG, domain) -> FixpointResult:
    in_states: Dict[int, Any] = {cfg.entry: domain.initial()}
    visits: Dict[int, int] = {}
    worklist = deque([cfg.entry])
    queued = {cfg.entry}
    iterations = 0
    converged = True
    exc_edge = getattr(domain, "exc_edge", None)

    while worklist:
        idx = worklist.popleft()
        queued.discard(idx)
        iterations += 1
        visits[idx] = visits.get(idx, 0) + 1
        if visits[idx] > _MAX_VISITS_PER_NODE:
            converged = False
            continue
        pre = in_states[idx]
        node = cfg.nodes[idx]
        post = domain.transfer(node, pre)
        for dst, kind, assume in cfg.succ[idx]:
            if kind == EXC:
                carry = exc_edge(node, pre) if exc_edge is not None else pre
            else:
                carry = post
            if assume is not None:
                carry = domain.assume(carry, assume)
            prev = in_states.get(dst)
            nxt = carry if prev is None else domain.join(prev, carry)
            if prev is None or nxt != prev:
                in_states[dst] = nxt
                if dst not in queued:
                    queued.add(dst)
                    worklist.append(dst)
    return FixpointResult(in_states, iterations, converged)
