"""Whole-program analysis engine: project index, import resolution,
remote call graph, and the content-hash file cache.

The engine owns the project-wide pass:

1. **Discover + parse** every file (``iter_python_files``), running the
   per-file rules (:mod:`.local`, minus GC008 which is re-derived on the
   real call graph) and the fact extractor (:mod:`.summary`) on each.
   Both outputs are cached keyed by the file's content hash, so repeat
   runs only re-parse files whose bytes changed.
2. **Index** the summaries: module table keyed by root-relative dotted
   name, functions/classes by fully-qualified name, and a resolver that
   follows imports (including package ``__init__`` re-export chains and
   relative imports) to the defining module.
3. **Remote call graph**: which functions are ``@remote`` tasks / actor
   methods, which call sites submit to which, and where blocking
   ``get()`` waits occur. GC010 walks its synchronous-wait edges for
   cycles; GC008 uses its bind-site resolution; ``graftcheck graph``
   dumps it as DOT.
4. **Project rule passes** (:mod:`.rules_project`, :mod:`.rules_spmd`)
   run over the index every time — they are dict-walks over cached
   facts, which is what keeps warm runs under the lint.sh budget.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .local import LOCAL_RULES, RULES, Finding, _FileChecker, \
    iter_python_files
from .summary import SUMMARY_VERSION, extract, suppressed

# Any change to local-rule or extraction logic must bump one of these:
# the pair keys every cache entry.
ENGINE_VERSION = 4  # v4: concurrency findings + lock facts in entries
CACHE_VERSION = f"{ENGINE_VERSION}.{SUMMARY_VERSION}"

SHARD_MAP_FQS = {
    "ray_tpu.jax_compat.shard_map",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
}

# the repo's lowering wrappers, under every re-export path a consumer
# can import them from (fixture trees don't scan the real package, so
# resolution stops at the import target rather than the defining file)
LOWER_SHARD_MAP_FQS = {
    "ray_tpu.parallel.sharding.lower.lower_shard_map",
    "ray_tpu.parallel.sharding.lower_shard_map",
    "ray_tpu.parallel.lower_shard_map",
}
LOWER_JIT_FQS = {
    "ray_tpu.parallel.sharding.lower.lower_jit",
    "ray_tpu.parallel.sharding.lower_jit",
    "ray_tpu.parallel.lower_jit",
}


def reverse_dependency_closure(index: "ProjectIndex",
                               paths: Sequence[str]) -> Set[str]:
    """`paths` plus every indexed file that transitively imports one of
    them (absolute paths). Drives ``--diff``: a changed file re-lints
    itself and everything whose cross-file facts could see the change —
    re-export chains count, since the package ``__init__`` imports the
    changed module and downstream files import the ``__init__``."""
    abspaths = {os.path.abspath(p) for p in paths}
    path_to_mod = {os.path.abspath(s["path"]): s["module"]
                   for s in index.summaries}
    rdeps: Dict[str, Set[str]] = {}
    for s in index.summaries:
        for fq in s["imports"].values():
            mod, _rest = index._split_module(fq)
            if mod is not None and mod != s["module"]:
                rdeps.setdefault(mod, set()).add(s["module"])
    seed = {path_to_mod[p] for p in abspaths if p in path_to_mod}
    closed = set(seed)
    work = list(seed)
    while work:
        m = work.pop()
        for dep in rdeps.get(m, ()):
            if dep not in closed:
                closed.add(dep)
                work.append(dep)
    return {p for p, m in path_to_mod.items() if m in closed}


def default_cache_path() -> str:
    env = os.environ.get("GRAFTCHECK_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "graftcheck",
                        "cache.json")


# ---------------------------------------------------------------------------
# project index


class ProjectIndex:
    """Symbol table over a set of file summaries."""

    def __init__(self, summaries: Sequence[Dict[str, Any]]):
        self.summaries = list(summaries)
        self.modules: Dict[str, Dict[str, Any]] = {}
        self.functions: Dict[str, Tuple[Dict[str, Any], Dict[str, Any]]] = {}
        self.classes: Dict[str, Tuple[Dict[str, Any], Dict[str, Any]]] = {}
        for s in summaries:
            m = s["module"]
            self.modules[m] = s
            for q, fn in s["functions"].items():
                self.functions[f"{m}.{q}"] = (s, fn)
            for cname, crec in s["classes"].items():
                self.classes[f"{m}.{cname}"] = (s, crec)

    # -- name resolution ---------------------------------------------------

    def _split_module(self, fq: str) -> Tuple[Optional[str], str]:
        """Longest known module prefix of `fq` -> (module, rest)."""
        parts = fq.split(".")
        for i in range(len(parts), 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                return mod, ".".join(parts[i:])
        return None, fq

    def canonical(self, fq: str, depth: int = 8) -> str:
        """Follow re-export chains (``from .dag import InputNode`` in a
        package ``__init__``) to the defining module."""
        for _ in range(depth):
            mod, rest = self._split_module(fq)
            if mod is None or not rest:
                return fq
            s = self.modules[mod]
            head = rest.split(".", 1)[0]
            tail = rest.split(".", 1)[1] if "." in rest else ""
            if head in s["functions"] or head in s["classes"] \
                    or head in s["str_consts"] or head in s["tuple_consts"] \
                    or head in s["mesh_vars"] or head in s["module_unser"] \
                    or head in s["handles"] or head in s["int_consts"] \
                    or head in s["int_tuple_consts"] \
                    or head in s.get("logical_tables", ()):
                return fq
            if head in s["imports"]:
                fq = s["imports"][head] + (("." + tail) if tail else "")
                continue
            return fq
        return fq

    def _defined(self, fq: str) -> bool:
        if fq in self.functions or fq in self.classes or fq in self.modules:
            return True
        mod, rest = self._split_module(fq)
        if mod is None or "." in rest or not rest:
            return False
        s = self.modules[mod]
        return rest in s["str_consts"] or rest in s["tuple_consts"] \
            or rest in s["mesh_vars"] or rest in s["handles"] \
            or rest in s["int_consts"] or rest in s["int_tuple_consts"] \
            or rest in s.get("logical_tables", ())

    def resolve(self, summary: Dict[str, Any], name: str) -> str:
        """Dotted name as written in `summary`'s module -> canonical
        fully-qualified name (best effort; external names pass through)."""
        parts = name.split(".")
        imports = summary["imports"]
        if parts[0] in imports:
            rest = ".".join(parts[1:])
            fq = imports[parts[0]] + (("." + rest) if rest else "")
        else:
            fq = f"{summary['module']}.{name}"
        fq = self.canonical(fq)
        if not self._defined(fq) and "." in name:
            # string annotations are often written fully qualified
            # ("pkg.a.A") with no matching import — try as-absolute
            alt = self.canonical(name)
            if self._defined(alt):
                return alt
        return fq

    def resolve_function(self, summary: Dict[str, Any], name: str
                         ) -> Optional[str]:
        fq = self.resolve(summary, name)
        return fq if fq in self.functions else None

    def resolve_class(self, summary: Dict[str, Any], name: str
                      ) -> Optional[str]:
        fq = self.resolve(summary, name)
        return fq if fq in self.classes else None

    def lookup_str_const(self, summary: Dict[str, Any], name: str
                         ) -> Optional[str]:
        fq = self.resolve(summary, name)
        mod, rest = self._split_module(fq)
        if mod is None or "." in rest or not rest:
            return None
        return self.modules[mod]["str_consts"].get(rest)

    def lookup_mesh_axes(self, summary: Dict[str, Any], name: str
                         ) -> Optional[List[str]]:
        fq = self.resolve(summary, name)
        mod, rest = self._split_module(fq)
        if mod is None or "." in rest or not rest:
            return None
        s = self.modules[mod]
        return s["mesh_vars"].get(rest) \
            or ([*s["tuple_consts"][rest]] if rest in s["tuple_consts"]
                else None)

    def lookup_mesh_sizes(self, summary: Dict[str, Any], name: str
                          ) -> Optional[List[int]]:
        """Per-axis device counts of a mesh variable, when its device
        array shape was statically resolvable at the definition."""
        fq = self.resolve(summary, name)
        mod, rest = self._split_module(fq)
        if mod is None or "." in rest or not rest:
            return None
        return self.modules[mod]["mesh_shapes"].get(rest)

    def lookup_int_const(self, summary: Dict[str, Any], name: str
                         ) -> Optional[int]:
        fq = self.resolve(summary, name)
        mod, rest = self._split_module(fq)
        if mod is None or "." in rest or not rest:
            return None
        return self.modules[mod]["int_consts"].get(rest)

    def lookup_logical_table(self, summary: Dict[str, Any], name: str
                             ) -> Optional[Dict[str, Any]]:
        """A module-level logical-axis table (``LOGICAL_TO_AXES``-style
        dict or a ``logical_axes`` method's literal return), cross-file."""
        fq = self.resolve(summary, name)
        mod, rest = self._split_module(fq)
        if mod is None or not rest:
            return None
        return self.modules[mod].get("logical_tables", {}).get(rest)

    # -- actor concurrency -------------------------------------------------

    def single_concurrency(self, cls_fq: str) -> bool:
        """True unless any creation site passes max_concurrency > 1."""
        for s in self.summaries:
            for opt in s["actor_options"]:
                if self.resolve_class(s, opt["cls"]) != cls_fq:
                    continue
                mc = opt.get("max_concurrency")
                if mc is not None and mc > 1:
                    return False
        return True


# ---------------------------------------------------------------------------
# remote call graph


@dataclass
class Edge:
    src: str          # fq of the calling function ("mod.<module>" for
                      # driver-level code)
    dst: str          # fq of the submitted remote function/actor method
    path: str
    line: int
    sync: bool        # result synchronously get()-waited in the caller
    kind: str = "submit"   # submit | create | bind

    def key(self) -> Tuple:
        return (self.src, self.dst, self.path, self.line, self.kind)


@dataclass
class CallGraph:
    nodes: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    edges: List[Edge] = field(default_factory=list)

    def sync_adj(self) -> Dict[str, List[Edge]]:
        adj: Dict[str, List[Edge]] = {}
        for e in self.edges:
            if e.sync and e.kind == "submit":
                adj.setdefault(e.src, []).append(e)
        return adj


def resolve_call_target(index: ProjectIndex, summary: Dict[str, Any],
                        fn: Dict[str, Any], name: str) -> Optional[str]:
    """Resolve a plain call name to a project function fq (methods on
    ``self`` included), else None."""
    if name.startswith("self.") and fn.get("cls"):
        cand = f"{summary['module']}.{fn['cls']}.{name[5:]}"
        return cand if cand in index.functions else None
    return index.resolve_function(summary, name)


def _blocking_helper_call_lines(index: ProjectIndex,
                                summary: Dict[str, Any],
                                fn: Dict[str, Any]) -> Set[int]:
    """Lines where a submit's ref is handed straight to a helper that
    blocks in get() — `fetch_sync(h.m.remote(x))` is a synchronous wait
    even though no get() is lexically present (one level deep, matching
    the interprocedural GC001 upgrade)."""
    lines: Set[int] = set()
    for call in fn["calls"]:
        if not any(a.get("kind") == "submit" for a in call["args"]):
            continue
        callee = resolve_call_target(index, summary, fn, call["name"])
        if callee is None:
            continue
        _, cfn = index.functions[callee]
        if not cfn["is_remote"] and cfn["gets"]:
            lines.add(call["lineno"])
    return lines


def resolve_submit_target(index: ProjectIndex, summary: Dict[str, Any],
                          fn: Dict[str, Any], sub: Dict[str, Any]
                          ) -> Optional[Tuple[str, str]]:
    """-> (kind, dst_fq) where kind is 'task' | 'method' | 'create'."""
    if sub["form"] == "func":
        fq = index.resolve(summary, sub["name"])
        if fq in index.classes:
            return ("create", fq)
        if fq in index.functions and index.functions[fq][1]["is_remote"]:
            return ("task", fq)
        return None
    recv = sub.get("recv") or {}
    cls_written: Optional[str] = None
    if recv.get("kind") in ("name",) and recv.get("cls"):
        cls_written = recv["cls"]
    elif recv.get("kind") == "self" and recv.get("cls"):
        cls_written = recv["cls"]
    elif recv.get("kind") == "selfattr" and fn.get("cls"):
        crec = summary["classes"].get(fn["cls"])
        if crec:
            cls_written = crec["attr_handles"].get(recv.get("attr"))
    if not cls_written:
        return None
    cls_fq = index.resolve_class(summary, cls_written)
    if cls_fq is None:
        return None
    _, crec = index.classes[cls_fq]
    if sub.get("method") not in crec["methods"]:
        return None
    return ("method", f"{cls_fq}.{sub['method']}")


def build_call_graph(index: ProjectIndex) -> CallGraph:
    g = CallGraph()
    for fq, (s, fn) in index.functions.items():
        is_actor_method = bool(
            fn.get("cls")
            and s["classes"].get(fn["cls"], {}).get("is_actor"))
        if fn["is_remote"] or fn["submits"] or fn["gets"]:
            g.nodes.setdefault(fq, {
                "remote": fn["is_remote"],
                "actor_method": is_actor_method,
                "path": s["path"], "line": fn["lineno"],
                "cls": (f"{s['module']}.{fn['cls']}" if fn.get("cls")
                        else None)})
        helper_waits = _blocking_helper_call_lines(index, s, fn)
        for sub in fn["submits"]:
            tgt = resolve_submit_target(index, s, fn, sub)
            if tgt is None:
                continue
            kind, dst = tgt
            sync = bool(sub["sync"]) or sub["lineno"] in helper_waits
            # sync edges anchor at the get() (where the wait parks),
            # async ones at the submit
            line = sub["sync_line"] if sub["sync"] and sub["sync_line"] \
                else sub["lineno"]
            g.edges.append(Edge(
                src=fq, dst=dst, path=s["path"], line=line, sync=sync,
                kind="create" if kind == "create" else "submit"))
    # compiled-graph bind sites become 'bind' edges (driver -> method)
    for s in index.summaries:
        for b in s["bind_sites"]:
            if not b.get("resolved"):
                continue
            cls_fq = index.resolve_class(s, b["cls"])
            if cls_fq is None:
                continue
            g.edges.append(Edge(
                src=f"{s['module']}.<module>", dst=f"{cls_fq}.{b['method']}",
                path=s["path"], line=b["lineno"], sync=False, kind="bind"))
    # make every edge endpoint a node so DOT output is closed
    for e in g.edges:
        for n in (e.src, e.dst):
            if n not in g.nodes:
                info = index.functions.get(n)
                g.nodes[n] = {
                    "remote": bool(info and info[1]["is_remote"]),
                    "actor_method": bool(info and info[1].get("cls")),
                    "path": info[0]["path"] if info else "",
                    "line": info[1]["lineno"] if info else 0,
                    "cls": None}
    return g


def to_dot(graph: CallGraph) -> str:
    """Render the remote call graph as GraphViz DOT (for
    ``graftcheck graph``: debugging deadlock cycles and cgraph wiring)."""
    out = ["digraph remote_calls {", "  rankdir=LR;",
           "  node [fontsize=10];"]

    def q(s: str) -> str:
        return '"' + s.replace('"', '\\"') + '"'

    for name, info in sorted(graph.nodes.items()):
        shape = "box" if info.get("actor_method") else "ellipse"
        style = ' style=filled fillcolor="#e8f0fe"' if info.get("remote") \
            else ""
        label = name
        if info.get("path"):
            label += f"\\n{os.path.basename(info['path'])}:{info['line']}"
        out.append(f"  {q(name)} [shape={shape}{style} label={q(label)}];")
    for e in sorted(graph.edges, key=lambda e: e.key()):
        attrs = []
        if e.kind == "bind":
            attrs.append('color="#7b1fa2" label="bind"')
        elif e.kind == "create":
            attrs.append('style=dotted label="create"')
        elif e.sync:
            attrs.append(f'label="sync get L{e.line}"')
        else:
            attrs.append(f'style=dashed label="submit L{e.line}"')
        out.append(f"  {q(e.src)} -> {q(e.dst)} [{' '.join(attrs)}];")
    out.append("}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# the engine (discovery + cache + passes)


@dataclass
class ProjectResult:
    findings: List[Finding]
    errors: int
    files: List[str]
    parsed: int          # files parsed this run (cache misses)
    cached: int          # files served from cache
    index: ProjectIndex
    graph: CallGraph
    lifecycle_stats: Dict[str, int] = field(default_factory=dict)
    shape_stats: Dict[str, int] = field(default_factory=dict)
    concurrency_stats: Dict[str, int] = field(default_factory=dict)


def _module_name(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = [p for p in rel.replace("\\", "/").split("/") if p != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or os.path.basename(root)


def _common_root(paths: Sequence[str]) -> str:
    abspaths = [os.path.abspath(p) for p in paths]
    dirs = [p if os.path.isdir(p) else os.path.dirname(p) for p in abspaths]
    root = os.path.commonpath(dirs) if dirs else os.getcwd()
    # `graftcheck ray_tpu/` must still derive the package-qualified
    # module names (ray_tpu.x.y), or absolute self-imports resolve to
    # nothing and every cross-file rule silently dies: walk up past
    # directories that are themselves packages
    while os.path.exists(os.path.join(root, "__init__.py")):
        parent = os.path.dirname(root)
        if parent == root:
            break
        root = parent
    return root


def _load_cache(path: Optional[str]) -> Dict[str, Any]:
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != CACHE_VERSION:
            return {}
        return data.get("files", {})
    except (OSError, ValueError):
        return {}


_CACHE_MAX_ENTRIES = 4096


def _save_cache(path: Optional[str], prior: Dict[str, Any],
                files: Dict[str, Any]) -> None:
    if not path:
        return
    # merge over the prior entries (the shared default cache serves
    # multiple path sets); evict to the current run's files when the
    # merged map outgrows the bound
    merged = dict(prior)
    merged.update(files)
    if len(merged) > _CACHE_MAX_ENTRIES:
        merged = files
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": CACHE_VERSION, "files": merged}, f)
        os.replace(tmp, path)
    except OSError:
        pass  # caching is best-effort; never fail the lint for it


def check_project(paths: Sequence[str],
                  rules: Optional[Set[str]] = None,
                  cache_path: Optional[str] = None,
                  root: Optional[str] = None,
                  stderr=None) -> ProjectResult:
    """Run the full engine over `paths`: cached per-file rules + fact
    extraction, then the whole-program passes."""
    from . import rules_concurrency, rules_lifecycle, rules_project, \
        rules_shapes, rules_spmd

    stderr = stderr if stderr is not None else sys.stderr
    # None means "all rules"; an explicit empty set means none (the
    # graph subcommand wants the index without any rule passes)
    enabled = set(rules) if rules is not None else set(RULES)
    files = iter_python_files(paths)
    root = os.path.abspath(root) if root else _common_root(files or ["."])
    cache = _load_cache(cache_path)
    new_cache: Dict[str, Any] = {}

    local_findings: List[Finding] = []
    summaries: List[Dict[str, Any]] = []
    errors = 0
    parsed = cached = 0
    # every local rule except GC008 (recomputed on the call graph) runs
    # on cache misses regardless of --rules: entries stay filter-agnostic
    local_rules = (LOCAL_RULES - {"GC008"})

    for path in files:
        apath = os.path.abspath(path)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            errors += 1
            print(f"{path}: {e}", file=stderr)
            continue
        sha = hashlib.sha256(raw).hexdigest()
        ent = cache.get(apath)
        if ent and ent.get("sha") == sha and ent.get("root") == root:
            cached += 1
            summary = ent["summary"]
            summary["path"] = path   # report with the path as given
            findings = [Finding(**fd) for fd in ent["local"]]
        else:
            parsed += 1
            source = raw.decode("utf-8", errors="replace")
            module = _module_name(apath, root)
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                errors += 1
                print(f"{path}: parse error: {e}", file=stderr)
                continue
            checker = _FileChecker(path, source, tree, set(local_rules))
            findings = checker.run()
            summary, extra = extract(path, source, tree, module)
            findings.extend(extra)
            # the CFG/dataflow lifecycle pass (GC030-033), the
            # shape/spec pass (GC022, GC042-043 + shape facts) and the
            # concurrency pass (GC050/053/054 + lock facts) run at
            # parse time too: confirmed findings and pending facts ride
            # the same cache entry
            findings.extend(rules_lifecycle.analyze_module(tree, summary))
            findings.extend(rules_shapes.analyze_module(tree, summary))
            findings.extend(rules_concurrency.analyze_module(tree, summary))
        new_cache[apath] = {
            "sha": sha, "root": root,
            "local": [f.as_dict() for f in findings],
            "summary": summary,
        }
        summaries.append(summary)
        local_findings.extend(f for f in findings if f.rule in enabled)

    index = ProjectIndex(summaries)
    graph = build_call_graph(index)
    findings = list(local_findings)
    findings.extend(rules_project.run(index, graph, enabled))
    findings.extend(rules_spmd.run(index, enabled))
    findings.extend(rules_lifecycle.resolve_pending(index, enabled))
    findings.extend(rules_shapes.run(index, enabled))
    findings.extend(rules_concurrency.run(index, enabled))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    _save_cache(cache_path, cache, new_cache)
    return ProjectResult(findings=findings, errors=errors, files=files,
                         parsed=parsed, cached=cached, index=index,
                         graph=graph,
                         lifecycle_stats=rules_lifecycle.aggregate_stats(
                             summaries),
                         shape_stats=rules_shapes.aggregate_stats(
                             summaries),
                         concurrency_stats=rules_concurrency.aggregate_stats(
                             summaries))
