"""Per-function control-flow graphs from the AST (graftcheck v3).

The statement-level CFG that :mod:`.dataflow` runs its fixpoint over.
One node per executable event — simple statements, branch tests, loop
bindings, except-handler entries, ``with`` enter/exit — with two edge
kinds:

``flow``
    Normal sequential/branch control transfer. Carries the node's
    POST-state (its transfer function has applied).
``exc``
    The statement raised before (or instead of) completing. Carries the
    node's PRE-state — an acquire that raised acquired nothing, a
    release that raised released nothing. Every statement that can
    raise gets one, targeted at the innermost enclosing handler
    context (except dispatch, ``finally`` copy, ``with`` exit copy, or
    the function's exception exit).

Structure handled:

- ``if``/``elif``/``else`` — branch tests become ``test`` nodes whose
  outgoing flow edges carry *assume* labels (``("some", name)`` /
  ``("none", name)``) for the ``x is None`` / ``not x`` / bare-name
  shapes, giving the dataflow just enough condition sensitivity for
  the ``if blocks is None: return`` allocation-failure idiom.
- ``while``/``for`` + ``else`` — loop back edges, ``break`` skipping
  the ``else``, ``continue``; ``while True`` omits the false edge.
- ``try``/``except``/``else``/``finally`` — exception edges from every
  raising statement of the body to the except dispatch; handler
  bodies rejoin after the try (the *swallow* path) unless they
  re-raise; the ``finally`` body is **duplicated per continuation**
  (normal, raise, return, break, continue) so each abnormal exit is
  routed through its own copy — the classic duplication approach, with
  a node budget guarding pathological nesting.
- ``with`` (and ``async with``) — an enter node plus one synthetic
  ``with_exit`` node per continuation: acquire at entry, release
  guaranteed on every path out, which is exactly the invariant the
  lifecycle rules credit it for.
- ``return``/``break``/``continue``/``raise`` — routed through any
  enclosing ``finally``/``with`` copies to the right exit.

Generator functions (a ``yield`` in the function's own scope) are the
caller's job to skip — :func:`is_generator` decides; the lifecycle
pass skips them with a stat counter (suspended frames hold resources
across an unknowable caller-driven schedule).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

# node kinds
ENTRY = "entry"
EXIT = "exit"                # normal function exit (return / fall off)
RAISE_EXIT = "raise_exit"    # exception propagates out of the function
STMT = "stmt"
TEST = "test"                # if/while condition or for-iterator step
FOR_BIND = "for_bind"        # loop-target binding for one iteration
EXCEPT_ENTRY = "except_entry"
EXCEPT_DISPATCH = "except_dispatch"
WITH_ENTER = "with_enter"
WITH_EXIT = "with_exit"

FLOW = "flow"
EXC = "exc"

# finally/with duplication budget: beyond this the function is skipped
# (counted by the caller) rather than analyzed partially
MAX_NODES = 4000


class CFGTooLarge(Exception):
    pass


class Node:
    __slots__ = ("idx", "kind", "ast", "lineno")

    def __init__(self, idx: int, kind: str, ast_node: Optional[ast.AST],
                 lineno: int):
        self.idx = idx
        self.kind = kind
        self.ast = ast_node
        self.lineno = lineno

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{self.kind}@{self.idx} L{self.lineno}>"


# assume labels: (sense, name) with sense in {"some", "none"}
Assume = Optional[Tuple[str, str]]


class CFG:
    def __init__(self) -> None:
        self.nodes: List[Node] = []
        # idx -> [(dst, edge_kind, assume)]
        self.succ: Dict[int, List[Tuple[int, str, Assume]]] = {}
        self.entry = -1
        self.exit = -1
        self.raise_exit = -1

    def add_node(self, kind: str, ast_node: Optional[ast.AST] = None,
                 lineno: int = 0) -> int:
        if len(self.nodes) >= MAX_NODES:
            raise CFGTooLarge()
        n = Node(len(self.nodes), kind, ast_node, lineno)
        self.nodes.append(n)
        self.succ[n.idx] = []
        return n.idx

    def add_edge(self, src: int, dst: int, kind: str = FLOW,
                 assume: Assume = None) -> None:
        e = (dst, kind, assume)
        if e not in self.succ[src]:
            self.succ[src].append(e)


class _Ctx:
    """Where abnormal control transfers go from the current position."""
    __slots__ = ("on_return", "on_raise", "on_break", "on_continue")

    def __init__(self, on_return: int, on_raise: int,
                 on_break: Optional[int], on_continue: Optional[int]):
        self.on_return = on_return
        self.on_raise = on_raise
        self.on_break = on_break
        self.on_continue = on_continue

    def derive(self, **kw) -> "_Ctx":
        c = _Ctx(self.on_return, self.on_raise, self.on_break,
                 self.on_continue)
        for k, v in kw.items():
            setattr(c, k, v)
        return c


def is_generator(fndef: ast.AST) -> bool:
    """Yield/YieldFrom in the function's own scope (not nested defs)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fndef))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(n, (ast.Yield, ast.YieldFrom)):
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


_NO_RAISE_STMTS = (ast.Pass, ast.Break, ast.Continue, ast.Global,
                   ast.Nonlocal, ast.Import, ast.ImportFrom)
_RAISING_EXPRS = (ast.Call, ast.Attribute, ast.Subscript, ast.BinOp,
                  ast.Await, ast.Compare)


def _can_raise(stmt: ast.stmt) -> bool:
    if isinstance(stmt, _NO_RAISE_STMTS):
        return False
    if isinstance(stmt, (ast.Raise, ast.Assert, ast.Delete)):
        return True
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node is not stmt:
            break  # defs' bodies have their own CFGs
        if isinstance(node, _RAISING_EXPRS):
            return True
    return False


def _test_assumes(test: ast.expr) -> Tuple[Assume, Assume]:
    """(true-branch assume, false-branch assume) for the narrow shapes
    the lifecycle rules need condition sensitivity for."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        t, f = _test_assumes(test.operand)
        return f, t
    if isinstance(test, ast.BoolOp):
        # `a and b` true => every conjunct true (any one assume is
        # sound); which conjunct made it false is unknown. Dual for or.
        if isinstance(test.op, ast.And):
            for v in test.values:
                t, _ = _test_assumes(v)
                if t is not None:
                    return t, None
        else:
            for v in test.values:
                _, f = _test_assumes(v)
                if f is not None:
                    return None, f
        return None, None
    if isinstance(test, ast.Name):
        return ("some", test.id), ("none", test.id)
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Attribute) \
            and test.func.attr in ("acquire", "locked"):
        # `if lock.acquire(blocking=False):` — the false branch did NOT
        # take the lock (try-acquire); dotted receiver keys the resource.
        # `if lock.locked():` is the dual probe: code guards bodies with
        # it to assert the caller-held invariant, so the true branch is
        # treated as held (v5 concurrency domain; see rules_concurrency).
        parts: List[str] = []
        node = test.func.value
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            dotted = ".".join(reversed(parts))
            return ("held", dotted), ("unheld", dotted)
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return ("none", test.left.id), ("some", test.left.id)
        if isinstance(test.ops[0], ast.IsNot):
            return ("some", test.left.id), ("none", test.left.id)
    return None, None


def handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler neither re-raises nor raises anything —
    the exception dies here and control rejoins the normal flow (the
    GC005/GC032 swallow shape)."""
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return False
    return True


class _Builder:
    def __init__(self, fndef: ast.AST):
        self.cfg = CFG()
        self.fndef = fndef

    def build(self) -> CFG:
        g = self.cfg
        g.exit = g.add_node(EXIT, lineno=getattr(self.fndef, "lineno", 0))
        g.raise_exit = g.add_node(RAISE_EXIT)
        ctx = _Ctx(on_return=g.exit, on_raise=g.raise_exit,
                   on_break=None, on_continue=None)
        first = self._block(self.fndef.body, g.exit, ctx)
        g.entry = g.add_node(ENTRY,
                             lineno=getattr(self.fndef, "lineno", 0))
        g.add_edge(g.entry, first)
        return g

    # -- blocks ------------------------------------------------------------

    def _block(self, stmts: Sequence[ast.stmt], follow: int,
               ctx: _Ctx) -> int:
        entry = follow
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, ctx)
        return entry

    # -- statements --------------------------------------------------------

    def _stmt(self, stmt: ast.stmt, follow: int, ctx: _Ctx) -> int:
        g = self.cfg
        if isinstance(stmt, ast.If):
            return self._if(stmt, follow, ctx)
        if isinstance(stmt, ast.While):
            return self._while(stmt, follow, ctx)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, follow, ctx)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, follow, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, follow, ctx)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, follow, ctx)

        n = g.add_node(STMT, stmt, stmt.lineno)
        if isinstance(stmt, ast.Return):
            g.add_edge(n, ctx.on_return)
            g.add_edge(n, ctx.on_raise, EXC)
        elif isinstance(stmt, ast.Raise):
            g.add_edge(n, ctx.on_raise, EXC)
        elif isinstance(stmt, ast.Break):
            g.add_edge(n, ctx.on_break
                       if ctx.on_break is not None else follow)
        elif isinstance(stmt, ast.Continue):
            g.add_edge(n, ctx.on_continue
                       if ctx.on_continue is not None else follow)
        else:
            g.add_edge(n, follow)
            if _can_raise(stmt):
                g.add_edge(n, ctx.on_raise, EXC)
        return n

    def _if(self, stmt: ast.If, follow: int, ctx: _Ctx) -> int:
        g = self.cfg
        t = g.add_node(TEST, stmt.test, stmt.lineno)
        then_entry = self._block(stmt.body, follow, ctx)
        else_entry = self._block(stmt.orelse, follow, ctx)
        a_true, a_false = _test_assumes(stmt.test)
        g.add_edge(t, then_entry, FLOW, a_true)
        g.add_edge(t, else_entry, FLOW, a_false)
        if _can_raise_expr(stmt.test):
            g.add_edge(t, ctx.on_raise, EXC)
        return t

    def _while(self, stmt: ast.While, follow: int, ctx: _Ctx) -> int:
        g = self.cfg
        t = g.add_node(TEST, stmt.test, stmt.lineno)
        body_ctx = ctx.derive(on_break=follow, on_continue=t)
        body_entry = self._block(stmt.body, t, body_ctx)
        a_true, a_false = _test_assumes(stmt.test)
        g.add_edge(t, body_entry, FLOW, a_true)
        always = isinstance(stmt.test, ast.Constant) and bool(stmt.test.value)
        if not always:
            else_entry = self._block(stmt.orelse, follow, ctx)
            g.add_edge(t, else_entry, FLOW, a_false)
        if _can_raise_expr(stmt.test):
            g.add_edge(t, ctx.on_raise, EXC)
        return t

    def _for(self, stmt, follow: int, ctx: _Ctx) -> int:
        g = self.cfg
        it = g.add_node(TEST, stmt.iter, stmt.lineno)
        bind = g.add_node(FOR_BIND, stmt, stmt.lineno)
        body_ctx = ctx.derive(on_break=follow, on_continue=it)
        body_entry = self._block(stmt.body, it, body_ctx)
        else_entry = self._block(stmt.orelse, follow, ctx)
        g.add_edge(it, bind)              # next item produced
        g.add_edge(it, else_entry)        # iterator exhausted
        g.add_edge(it, ctx.on_raise, EXC)
        g.add_edge(bind, body_entry)
        g.add_edge(bind, ctx.on_raise, EXC)
        return it

    def _match(self, stmt: ast.Match, follow: int, ctx: _Ctx) -> int:
        g = self.cfg
        t = g.add_node(TEST, stmt.subject, stmt.lineno)
        for case in stmt.cases:
            g.add_edge(t, self._block(case.body, follow, ctx))
        g.add_edge(t, follow)  # no case matched
        if _can_raise_expr(stmt.subject):
            g.add_edge(t, ctx.on_raise, EXC)
        return t

    def _try(self, stmt: ast.Try, follow: int, ctx: _Ctx) -> int:
        g = self.cfg

        def fin(cont: Optional[int]) -> Optional[int]:
            """A fresh copy of the finally body flowing into `cont`."""
            if cont is None:
                return None
            if not stmt.finalbody:
                return cont
            return self._block(stmt.finalbody, cont, ctx)

        fin_norm = fin(follow)
        fin_raise = fin(ctx.on_raise)
        inner = ctx.derive(on_raise=fin_raise, on_return=fin(ctx.on_return),
                           on_break=fin(ctx.on_break),
                           on_continue=fin(ctx.on_continue))

        if stmt.handlers:
            dispatch = g.add_node(EXCEPT_DISPATCH, stmt, stmt.lineno)
            for handler in stmt.handlers:
                h = g.add_node(EXCEPT_ENTRY, handler, handler.lineno)
                h_body = self._block(handler.body, fin_norm, inner)
                g.add_edge(h, h_body)
                g.add_edge(h, inner.on_raise, EXC)
                g.add_edge(dispatch, h)
            # no handler matched: the exception keeps propagating
            g.add_edge(dispatch, fin_raise)
            body_raise = dispatch
        else:
            body_raise = fin_raise

        body_ctx = inner.derive(on_raise=body_raise)
        # the else clause runs after the body completes; its exceptions
        # are NOT caught by this try's handlers
        else_entry = self._block(stmt.orelse, fin_norm, inner)
        return self._block(stmt.body, else_entry, body_ctx)

    def _with(self, stmt, follow: int, ctx: _Ctx) -> int:
        # `with a, b:` is sugar for nested single-item withs
        return self._with_items(stmt, list(stmt.items), follow, ctx)

    def _with_items(self, stmt, items: List[ast.withitem], follow: int,
                    ctx: _Ctx) -> int:
        g = self.cfg
        item = items[0]

        def wexit(cont: Optional[int]) -> Optional[int]:
            if cont is None:
                return None
            n = g.add_node(WITH_EXIT, item, stmt.lineno)
            g.add_edge(n, cont)
            return n

        ex_norm = wexit(follow)
        inner = _Ctx(on_return=wexit(ctx.on_return),
                     on_raise=wexit(ctx.on_raise),
                     on_break=wexit(ctx.on_break),
                     on_continue=wexit(ctx.on_continue))
        if len(items) == 1:
            body_entry = self._block(stmt.body, ex_norm, inner)
        else:
            body_entry = self._with_items(stmt, items[1:], ex_norm, inner)
        enter = g.add_node(WITH_ENTER, item, stmt.lineno)
        g.add_edge(enter, body_entry)
        g.add_edge(enter, ctx.on_raise, EXC)
        return enter


def _can_raise_expr(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, _RAISING_EXPRS):
            return True
    return False


def build_cfg(fndef: ast.AST) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef. Raises
    :class:`CFGTooLarge` past the duplication budget."""
    return _Builder(fndef).build()
