"""Shape-and-spec abstract interpretation (graftcheck v4, GC040-044 +
the path-sensitive GC022).

Rides the v3 CFG/dataflow fixpoint (:mod:`.cfg`, :mod:`.dataflow`) and
the v2 project index exactly like :mod:`.rules_lifecycle`: the
module-local half runs at extraction time and its findings/facts ride
the content-hash cache; the cross-file half is a dict-walk over cached
facts at project time.

Extraction time (``analyze_module``):

GC022
    Donated-buffer read, now on the CFG: a name passed at a
    ``donate_argnums`` position of a jitted call and read on a path
    *after* the donation. A read only on the untaken branch no longer
    flags; a read reachable through an except edge now does (exception
    edges carry the donated state into handlers).

GC042
    Pallas kernel consistency, structural per call site:
    ``index_map`` arity vs grid rank, ``index_map`` return rank vs
    ``block_shape`` rank, kernel parameter count vs wired refs,
    block divisibility of the out shape, and constant/identity
    out-of-bounds index maps — each checked only when every number
    involved resolves statically. Sites using ``grid_spec=`` are
    skipped (scalar-prefetch grids pass extra index args by design).

GC043
    Codec pairing on wire paths: a ``quantize``/``quantize_blocks``
    payload reaching a reduce (``psum``/``psum_scatter``/``jnp.sum``/
    ...) before any ``dequantize``/``astype`` — reducing packed
    codewords sums bits, not values. A quantized payload handed to a
    point-to-point send whose module never decodes anything fires the
    module-pairing form at the send line. Keyed off
    :func:`.shapes.classify_codec`, the same single-classifier
    extension point the GC030 lifecycle vocabulary uses.

Shape facts: array shapes from literal constructors propagate through
the same fixpoint, and the first statically-visible invocation of each
``shard_map``/``lower_shard_map``/``lower_jit`` site records its
argument shapes onto the site (``site["call_shapes"]``) for the
project pass.

Project time (``run``):

GC040
    Mesh-axis divisibility: an ``in_specs`` entry shards a dim whose
    statically-known size the bound mesh axis size does not divide —
    GSPMD pads every shard silently.

GC041
    Sharded contraction dim: a ``dot_general``/einsum/matmul
    contraction dim of the wrapped function carries a non-``None``
    spec entry — the SpecLayout invariant from
    ``parallel/sharding/layout.py`` ("contraction dims never shard"),
    checked at every lowering site with ``spec_for_logical`` tables
    resolved cross-file.

GC044
    Collective geometry: a ``psum_scatter``/``all_to_all`` inside the
    wrapped body splits a per-shard dim the mesh axis size does not
    divide, where shapes, specs, and mesh all resolve.
"""
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from . import dataflow, shapes
from .cfg import (CFGTooLarge, ENTRY, EXCEPT_DISPATCH, EXCEPT_ENTRY, EXIT,
                  FOR_BIND, RAISE_EXIT, TEST, WITH_ENTER, WITH_EXIT,
                  build_cfg, is_generator)
from .local import Finding, _assigned_names
from .rules_lifecycle import (_own_scope_stmts, _params_of, _walk_expr,
                              collect_functions)
from .summary import _jit_donate_positions, suppressed

__all__ = ["analyze_module", "run", "aggregate_stats"]


# ---------------------------------------------------------------------------
# small AST helpers


def _dotted_last(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _expr_nodes(stmt: ast.AST):
    """Expression nodes of one simple statement, nested scopes pruned."""
    for child in ast.iter_child_nodes(stmt):
        if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
            yield from _walk_expr(child)


def _module_fn(tree: ast.Module) -> Optional[ast.AST]:
    """Module-scope statements wrapped as a synthetic function so the
    CFG builder can run over driver-level code too."""
    body = [s for s in tree.body
            if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Import,
                                  ast.ImportFrom))]
    if not body:
        return None
    tmpl = ast.parse("def _m():\n    pass").body[0]
    tmpl.name = "<module>"
    tmpl.body = body
    ast.copy_location(tmpl, body[0])
    return tmpl


# ---------------------------------------------------------------------------
# the CFG domain (GC022 + GC043 + shape facts)


class _ShapeDomain:
    """State: dict name -> frozenset of facts (see :mod:`.shapes`)."""

    def __init__(self, analysis: "_FunctionAnalysis"):
        self.a = analysis

    def initial(self) -> Dict[str, Any]:
        return {}

    def join(self, x: Dict[str, Any], y: Dict[str, Any]) -> Dict[str, Any]:
        return shapes.join_env(x, y)

    def assume(self, state: Dict[str, Any], label) -> Dict[str, Any]:
        return state

    def transfer(self, node, state: Dict[str, Any]) -> Dict[str, Any]:
        kind = node.kind
        if kind in (ENTRY, EXIT, RAISE_EXIT, EXCEPT_DISPATCH,
                    EXCEPT_ENTRY, WITH_EXIT) or node.ast is None:
            return state
        a = self.a
        if kind == FOR_BIND:
            new = dict(state)
            for nm in _assigned_names(node.ast.target):
                new.pop(nm, None)
            return new
        if kind == TEST:
            a.check_exprs(_walk_expr(node.ast), state)
            return state
        if kind == WITH_ENTER:
            item = node.ast
            a.check_exprs(_walk_expr(item.context_expr), state)
            if item.optional_vars is not None:
                new = dict(state)
                for nm in _assigned_names(item.optional_vars):
                    new.pop(nm, None)
                return new
            return state
        # STMT
        stmt = node.ast
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state
        nodes = list(_expr_nodes(stmt))
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.target,
                                                          ast.Name):
            a.check_name(stmt.target.id, stmt.target.lineno, state)
        a.check_exprs(nodes, state)
        new = dict(state)
        # call effects: donation marks, before stores rebind
        for n in nodes:
            if isinstance(n, ast.Call):
                a.call_effects(n, state, new)
        # stores
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], (ast.Tuple, ast.List)) \
                and isinstance(stmt.value, ast.Call) \
                and shapes.classify_codec(stmt.value) == "encode":
            # `payload, scales = quantize_blocks(x)`: every piece of the
            # unpacked result carries the encoding until decoded
            for nm in _assigned_names(stmt.targets[0]):
                new[nm] = frozenset({("quant", stmt.value.lineno)})
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            facts = a.value_facts(stmt.value, state)
            nm = stmt.targets[0].id
            if facts:
                new[nm] = facts
            else:
                new.pop(nm, None)
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            facts = a.value_facts(stmt.value, state) if stmt.value else \
                shapes.EMPTY
            if facts:
                new[stmt.target.id] = facts
            else:
                new.pop(stmt.target.id, None)
        else:
            for tgt in getattr(stmt, "targets", []) or \
                    ([stmt.target] if isinstance(stmt, ast.AugAssign)
                     else []):
                for nm in _assigned_names(tgt):
                    new.pop(nm, None)
        return new


class _FunctionAnalysis:
    def __init__(self, fndef: ast.AST, qname: str, summary: Dict[str, Any],
                 env: shapes.ConstEnv, sites_by_line: Dict[int, Dict],
                 findings: List[Finding], events: Dict[str, Any]):
        self.fndef = fndef
        self.qname = qname
        self.summary = summary
        self.env = env
        self.sites_by_line = sites_by_line
        self.findings = findings
        self.events = events
        self.donated: Dict[str, Tuple[int, ...]] = {}
        self.has_encode = False
        self.has_site = False
        self._reported: Set[Tuple] = set()

    # -- reporting ---------------------------------------------------------

    def report(self, rule: str, line: int, col: int, message: str) -> None:
        key = (rule, line, message[:48])
        if key in self._reported:
            return
        if suppressed(self.summary, line, rule):
            return
        self._reported.add(key)
        self.findings.append(Finding(
            path=self.summary["path"], line=line, col=col, rule=rule,
            message=message))

    # -- prescan -----------------------------------------------------------

    def prescan(self) -> bool:
        """Donated callables + interest check; False when the fixpoint
        has nothing to track in this function."""
        own = list(_own_scope_stmts(self.fndef))
        for st in own:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.value, ast.Call):
                pos = _jit_donate_positions(st.value)
                if pos:
                    tgt = st.targets[0]
                    if isinstance(tgt, ast.Name):
                        self.donated[tgt.id] = pos
                    elif isinstance(tgt, ast.Attribute):
                        d = _attr_dotted(tgt)
                        if d:
                            self.donated[d] = pos
            for n in _expr_nodes(st) if not isinstance(
                    st, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)) else ():
                if isinstance(n, ast.Call):
                    if shapes.classify_codec(n) == "encode":
                        self.has_encode = True
                    if n.lineno in self.sites_by_line:
                        self.has_site = True
        # nested defs carrying @partial(jax.jit, donate_argnums=...)
        for st in _child_defs_of(self.fndef):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in st.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _jit_donate_positions(dec)
                        if pos:
                            self.donated[st.name] = pos
        return bool(self.donated or self.has_encode or self.has_site)

    # -- domain callbacks --------------------------------------------------

    def check_name(self, name: str, lineno: int,
                   state: Dict[str, Any]) -> None:
        dl = shapes.donated_line(state.get(name, shapes.EMPTY))
        if dl is not None:
            self.report(
                "GC022", lineno, 1,
                f"'{name}' was donated to the jitted call at line {dl} "
                f"(donate_argnums) and is read here afterwards; XLA may "
                f"have reused its buffer — rebind the result to the same "
                f"name or drop the donation")

    def check_exprs(self, nodes, state: Dict[str, Any]) -> None:
        for n in nodes:
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                self.check_name(n.id, n.lineno, state)

    def call_effects(self, call: ast.Call, pre: Dict[str, Any],
                     new: Dict[str, Any]) -> None:
        # donation: `jitted(x)` / `jax.jit(f, donate_argnums=...)(x)`
        positions: Optional[Tuple[int, ...]] = None
        fd = _call_target_dotted(call.func)
        if fd is not None and fd in self.donated:
            positions = self.donated[fd]
        elif isinstance(call.func, ast.Call):
            positions = _jit_donate_positions(call.func)
        if positions:
            for p in positions:
                if p < len(call.args) and isinstance(call.args[p],
                                                     ast.Name):
                    nm = call.args[p].id
                    new[nm] = frozenset(
                        {("donated", call.lineno)}
                        | {f for f in new.get(nm, shapes.EMPTY)
                           if f[0] != "donated"})
        cls = shapes.classify_codec(call)
        if cls == "reduce" and call.args \
                and isinstance(call.args[0], ast.Name):
            nm = call.args[0].id
            ql = shapes.quant_line(pre.get(nm, shapes.EMPTY))
            if ql is not None:
                op = _dotted_last(call.func)
                self.report(
                    "GC043", call.lineno, call.col_offset + 1,
                    f"{op}() reduces '{nm}', which still carries the "
                    f"quantized wire encoding from line {ql}: reducing "
                    f"packed payloads sums codewords, not values — "
                    f"dequantize before the reduce")
        elif cls == "send":
            for arg in call.args:
                if isinstance(arg, ast.Name):
                    ql = shapes.quant_line(pre.get(arg.id, shapes.EMPTY))
                    if ql is not None:
                        self.events.setdefault("quant_sends", []).append(
                            (call.lineno, call.col_offset + 1, arg.id))
        # shard_map-site invocation: attach argument shapes
        site = None
        if isinstance(call.func, ast.Name):
            ln = shapes.sm_site(pre.get(call.func.id, shapes.EMPTY))
            if ln is not None:
                site = self.sites_by_line.get(ln)
        elif isinstance(call.func, ast.Call) \
                and call.func.lineno in self.sites_by_line:
            site = self.sites_by_line.get(call.func.lineno)
        if site is not None and site.get("call_shapes") is None:
            shps = []
            for arg in call.args:
                if isinstance(arg, ast.Name):
                    shp = shapes.shape_of(pre.get(arg.id, shapes.EMPTY))
                elif isinstance(arg, ast.Call):
                    shp = shapes.shape_from_call(arg, self.env)
                else:
                    shp = shapes.eval_shape(arg, self.env) \
                        if isinstance(arg, (ast.Tuple, ast.List)) else None
                shps.append(list(shp) if shp is not None else None)
            if any(s is not None for s in shps):
                site["call_shapes"] = shps
                self.events["sites_shaped"] = \
                    self.events.get("sites_shaped", 0) + 1

    def value_facts(self, value: Optional[ast.AST],
                    state: Dict[str, Any]) -> Any:
        if value is None:
            return shapes.EMPTY
        if isinstance(value, ast.Name):
            return state.get(value.id, shapes.EMPTY)
        if isinstance(value, ast.Call):
            cls = shapes.classify_codec(value)
            if cls == "encode":
                return frozenset({("quant", value.lineno)})
            if cls == "wire":
                src = value.args[0] if value.args else None
                if isinstance(src, ast.Name):
                    ql = shapes.quant_line(state.get(src.id, shapes.EMPTY))
                    if ql is not None:
                        return frozenset({("quant", ql)})
                return shapes.EMPTY
            if cls in ("decode", "reduce"):
                return shapes.EMPTY
            if value.lineno in self.sites_by_line:
                if isinstance(value.func, ast.Call):
                    return shapes.EMPTY   # result of invoking the site
                # the site call itself (`shard_map(f, ...)`, a lowering
                # wrapper, or a partial-bound shard_map applied to its
                # body fn) — the bound name carries the site
                return frozenset({("sm", value.lineno)})
            shp = shapes.shape_from_call(value, self.env)
            if shp is not None:
                return frozenset({("shape", shp)})
            return shapes.EMPTY
        return shapes.EMPTY

    # -- run ---------------------------------------------------------------

    def run(self, stats: Dict[str, int]) -> None:
        if not self.prescan():
            stats["fns_trivial"] = stats.get("fns_trivial", 0) + 1
            return
        try:
            graph = build_cfg(self.fndef)
        except CFGTooLarge:
            stats["fns_too_large"] = stats.get("fns_too_large", 0) + 1
            return
        stats["fns_analyzed"] = stats.get("fns_analyzed", 0) + 1
        stats["cfg_nodes"] = stats.get("cfg_nodes", 0) + len(graph.nodes)
        result = dataflow.run(graph, _ShapeDomain(self))
        stats["fixpoint_iterations"] = \
            stats.get("fixpoint_iterations", 0) + result.iterations
        if not result.converged:
            stats["fns_nonconverged"] = \
                stats.get("fns_nonconverged", 0) + 1


def _attr_dotted(node: ast.Attribute) -> Optional[str]:
    parts: List[str] = [node.attr]
    cur = node.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _call_target_dotted(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return _attr_dotted(func)
    return None


def _child_defs_of(fndef: ast.AST) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    stack: List[ast.stmt] = list(fndef.body)
    while stack:
        st = stack.pop(0)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            out.append(st)
            continue
        for fld in ("body", "orelse", "finalbody"):
            child = getattr(st, fld, None)
            if isinstance(child, list):
                stack.extend(c for c in child if isinstance(c, ast.stmt))
        for handler in getattr(st, "handlers", ()):
            stack.extend(handler.body)
        for case in getattr(st, "cases", ()):
            stack.extend(case.body)
    return out


# ---------------------------------------------------------------------------
# GC042 — Pallas kernel consistency (structural, per call site)


def _gc042_sites(fndef: ast.AST) -> List[ast.Call]:
    out = []
    for st in _own_scope_stmts(fndef):
        for n in _expr_nodes(st):
            if isinstance(n, ast.Call) \
                    and _dotted_last(n.func) == "pallas_call":
                out.append(n)
    return out


def _block_spec(call: ast.Call) -> Optional[Dict[str, Any]]:
    """A ``pl.BlockSpec(block_shape, index_map)`` call -> its parsed
    pieces; None for non-BlockSpec elements (``pl.ANY``, None, ...)."""
    if not (isinstance(call, ast.Call)
            and _dotted_last(call.func) == "BlockSpec"):
        return None
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    block = kw.get("block_shape") or (call.args[0] if call.args else None)
    imap = kw.get("index_map") or (call.args[1] if len(call.args) > 1
                                   else None)
    rec: Dict[str, Any] = {"lineno": call.lineno,
                           "col": call.col_offset + 1,
                           "block": None, "arity": None, "ret": None}
    if isinstance(block, (ast.Tuple, ast.List)):
        rec["block"] = list(block.elts)
    if isinstance(imap, ast.Lambda):
        a = imap.args
        rec["arity"] = len(a.posonlyargs) + len(a.args)
        rec["params"] = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        body = imap.body
        rec["ret"] = list(body.elts) if isinstance(body, ast.Tuple) \
            else [body]
    return rec


def _out_shapes(expr: Optional[ast.AST], env: shapes.ConstEnv
                ) -> Optional[List[Optional[Tuple]]]:
    """out_shape= -> list of per-output shape tuples (None entries for
    unresolvable shapes); None when the output count itself is unknown."""
    if expr is None:
        return None

    def one(e: ast.AST) -> Optional[Tuple]:
        if isinstance(e, ast.Call) \
                and _dotted_last(e.func) == "ShapeDtypeStruct":
            kw = {k.arg: k.value for k in e.keywords if k.arg}
            shp = kw.get("shape") or (e.args[0] if e.args else None)
            return shapes.eval_shape(shp, env)
        return None

    if isinstance(expr, (ast.Tuple, ast.List)):
        return [one(e) for e in expr.elts]
    if isinstance(expr, ast.Call) \
            and _dotted_last(expr.func) == "ShapeDtypeStruct":
        return [one(expr)]
    return None


def _analyze_pallas_site(call: ast.Call, qname: str,
                         summary: Dict[str, Any], env: shapes.ConstEnv,
                         report) -> None:
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    if "grid_spec" in kw:
        return   # PrefetchScalarGridSpec &co pass extra index args
    # grid rank + dims
    grid_rank: Optional[int] = None
    grid_dims: Optional[List[Optional[int]]] = None
    g = kw.get("grid")
    if isinstance(g, (ast.Tuple, ast.List)):
        grid_rank = len(g.elts)
        grid_dims = [shapes.eval_int(e, env) for e in g.elts]
    elif g is not None:
        gs = shapes.eval_shape(g, env)
        if gs is not None:
            grid_rank = len(gs)
            grid_dims = list(gs)
    # in/out specs
    in_specs_expr = kw.get("in_specs")
    in_elts = list(in_specs_expr.elts) \
        if isinstance(in_specs_expr, (ast.Tuple, ast.List)) else None
    out_specs_expr = kw.get("out_specs")
    if isinstance(out_specs_expr, (ast.Tuple, ast.List)):
        out_elts: Optional[List[ast.AST]] = list(out_specs_expr.elts)
    elif out_specs_expr is not None:
        out_elts = [out_specs_expr]
    else:
        out_elts = None
    out_shapes = _out_shapes(kw.get("out_shape"), env)

    def check_spec(rec: Dict[str, Any],
                   arr_shape: Optional[Tuple]) -> None:
        if rec["arity"] is not None and grid_rank is not None \
                and rec["arity"] != grid_rank:
            report("GC042", rec["lineno"], rec["col"],
                   f"BlockSpec index_map takes {rec['arity']} "
                   f"argument(s) but the pallas_call grid has rank "
                   f"{grid_rank}; each grid axis passes exactly one "
                   f"block index — the kernel fails at trace time or "
                   f"reads the wrong blocks")
        if rec["ret"] is not None and rec["block"] is not None \
                and len(rec["ret"]) != len(rec["block"]):
            report("GC042", rec["lineno"], rec["col"],
                   f"BlockSpec block_shape has rank {len(rec['block'])} "
                   f"but its index_map returns {len(rec['ret'])} block "
                   f"ind{'ex' if len(rec['ret']) == 1 else 'ices'}; the "
                   f"ranks must match")
        if rec["block"] is None or arr_shape is None:
            return
        if len(rec["block"]) != len(arr_shape):
            report("GC042", rec["lineno"], rec["col"],
                   f"BlockSpec block_shape has rank {len(rec['block'])} "
                   f"but the array it buckets has rank {len(arr_shape)}")
            return
        for k, (bexpr, dim) in enumerate(zip(rec["block"], arr_shape)):
            if isinstance(bexpr, ast.Constant) and bexpr.value is None:
                continue
            b = shapes.eval_int(bexpr, env)
            if b is None or not isinstance(dim, int) or b <= 0:
                continue
            if dim % b != 0:
                report("GC042", rec["lineno"], rec["col"],
                       f"array dim {k} of size {dim} is not divisible "
                       f"by block_shape[{k}] = {b}: the trailing "
                       f"partial block reads out of bounds — pad the "
                       f"array or pick a dividing block")
                continue
            ret = rec["ret"][k] if rec["ret"] is not None \
                and len(rec["ret"]) == len(rec["block"]) else None
            if isinstance(ret, ast.Constant) \
                    and isinstance(ret.value, int):
                if (ret.value + 1) * b > dim:
                    report("GC042", rec["lineno"], rec["col"],
                           f"index_map returns constant block index "
                           f"{ret.value} along dim {k}: blocks of {b} "
                           f"reach element {(ret.value + 1) * b} but "
                           f"the array dim is {dim} — out of bounds")
            elif isinstance(ret, ast.Name) and grid_dims is not None \
                    and rec.get("params"):
                try:
                    p = rec["params"].index(ret.id)
                except ValueError:
                    continue
                gp = grid_dims[p] if p < len(grid_dims) else None
                if gp is not None and gp * b > dim:
                    report("GC042", rec["lineno"], rec["col"],
                           f"grid dim {p} of {gp} blocks times "
                           f"block_shape[{k}] = {b} covers "
                           f"{gp * b} elements but the array dim is "
                           f"{dim} — the last blocks read out of "
                           f"bounds")

    n_in = len(in_elts) if in_elts is not None else None
    for elt in in_elts or []:
        rec = _block_spec(elt)
        if rec is not None:
            check_spec(rec, None)
    if out_elts is not None:
        for o, elt in enumerate(out_elts):
            rec = _block_spec(elt)
            if rec is None:
                continue
            arr = out_shapes[o] if out_shapes is not None \
                and o < len(out_shapes) else None
            check_spec(rec, arr)
    # kernel arity vs wired refs
    n_out = len(out_shapes) if out_shapes is not None else None
    scratch = kw.get("scratch_shapes")
    if scratch is None:
        n_scratch: Optional[int] = 0
    elif isinstance(scratch, (ast.Tuple, ast.List)):
        n_scratch = len(scratch.elts)
    else:
        n_scratch = None
    kernel = call.args[0] if call.args else None
    n_params: Optional[int] = None
    kname = ""
    if isinstance(kernel, ast.Lambda):
        if kernel.args.vararg is None:
            n_params = len(kernel.args.posonlyargs) + len(kernel.args.args)
        kname = "<lambda>"
    elif isinstance(kernel, ast.Name):
        kname = kernel.id
        for cand in (f"{qname}.{kname}", kname):
            fnrec = summary["functions"].get(cand)
            if fnrec is not None and not fnrec["has_vararg"] \
                    and not fnrec.get("cls"):
                n_params = len(fnrec["params"])
                break
    if None not in (n_in, n_out, n_scratch, n_params) \
            and n_in + n_out + n_scratch != n_params:
        report("GC042", call.lineno, call.col_offset + 1,
               f"pallas_call wires {n_in + n_out + n_scratch} ref(s) "
               f"({n_in} in_specs + {n_out} output(s) + {n_scratch} "
               f"scratch) but kernel {kname}() takes {n_params} "
               f"parameter(s)")


# ---------------------------------------------------------------------------
# logical-axis table extraction (GC041 cross-file resolution)


def _dict_table(node: ast.AST) -> Optional[Dict[str, Any]]:
    """A literal ``{"name": None | "axis" | ("a", "b") | (...logical)}``
    dict -> JSON-able table; None when any piece is non-literal."""
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, Any] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        if isinstance(v, ast.Constant) and (v.value is None
                                            or isinstance(v.value, str)):
            out[k.value] = v.value
        elif isinstance(v, (ast.Tuple, ast.List)):
            elems = []
            for e in v.elts:
                if isinstance(e, ast.Constant) \
                        and (e.value is None or isinstance(e.value, str)):
                    elems.append(e.value)
                else:
                    return None
            out[k.value] = elems
        else:
            return None
    return out


def _collect_logical_tables(tree: ast.Module,
                            summary: Dict[str, Any]) -> None:
    tables: Dict[str, Any] = {}
    for st in tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            t = _dict_table(st.value)
            if t is not None:
                tables[st.targets[0].id] = t
    for fndef, qname, cls in collect_functions(tree):
        if fndef.name != "logical_axes":
            continue
        for st in _own_scope_stmts(fndef):
            if isinstance(st, ast.Return) and st.value is not None:
                t = _dict_table(st.value)
                if t is not None:
                    tables[qname] = t
    if tables:
        summary["logical_tables"] = tables


# ---------------------------------------------------------------------------
# module entry point (runs at extraction time; results ride the cache)


def analyze_module(tree: ast.Module, summary: Dict[str, Any]
                   ) -> List[Finding]:
    """GC022/GC042/GC043 plus shape-fact attachment over one module.
    Mutates `summary`:

    - ``summary["shapes"] = {"stats": {...}}`` (``--stats`` counters)
    - ``summary["logical_tables"]`` — literal axis tables (GC041)
    - ``site["call_shapes"]`` on shard_map sites whose invocation
      shapes resolved
    - ``summary["functions"][q]["shapes"]`` — contraction records
    """
    findings: List[Finding] = []
    stats: Dict[str, int] = {}
    events: Dict[str, Any] = {}
    _collect_logical_tables(tree, summary)
    sites_by_line = {site["lineno"]: site
                     for site in summary.get("shardmap", ())}
    menv = shapes.ConstEnv(summary)

    def report(rule: str, line: int, col: int, message: str) -> None:
        if suppressed(summary, line, rule):
            return
        findings.append(Finding(path=summary["path"], line=line, col=col,
                                rule=rule, message=message))

    units: List[Tuple[ast.AST, str, Optional[str]]] = \
        list(collect_functions(tree))
    mod_fn = _module_fn(tree)
    if mod_fn is not None:
        units.append((mod_fn, "<module>", None))

    for fndef, qname, cls in units:
        stats["fns_total"] = stats.get("fns_total", 0) + 1
        env = shapes.ConstEnv(summary)
        env.add_locals(_own_scope_stmts(fndef))
        # GC042 (structural)
        psites = _gc042_sites(fndef)
        if psites:
            stats["pallas_sites"] = \
                stats.get("pallas_sites", 0) + len(psites)
            for call in psites:
                try:
                    _analyze_pallas_site(call, qname, summary, env, report)
                except Exception:
                    stats["fns_errors"] = stats.get("fns_errors", 0) + 1
        # GC041 facts: contraction records for project-time resolution
        if qname != "<module>":
            try:
                recs = shapes.contraction_records(
                    fndef, _params_of(fndef), _own_scope_walk)
            except Exception:
                recs = []
            if recs:
                stats["contraction_fns"] = \
                    stats.get("contraction_fns", 0) + 1
                fnrec = summary["functions"].get(qname)
                if fnrec is not None:
                    fnrec["shapes"] = {"contractions": recs}
        # the CFG pass (GC022 + GC043 + shape facts)
        if qname != "<module>" and is_generator(fndef):
            stats["fns_generators_skipped"] = \
                stats.get("fns_generators_skipped", 0) + 1
            continue
        fa = _FunctionAnalysis(fndef, qname, summary, env, sites_by_line,
                               findings, events)
        try:
            fa.run(stats)
        except Exception:    # never fail the lint on one function
            stats["fns_errors"] = stats.get("fns_errors", 0) + 1

    # module-level codec pairing: a quantized payload sent point-to-point
    # with no decode anywhere on this module's receive legs
    sends = events.get("quant_sends", ())
    if sends and not _module_has_decode(tree):
        for line, col, name in sends:
            if suppressed(summary, line, "GC043"):
                continue
            findings.append(Finding(
                path=summary["path"], line=line, col=col, rule="GC043",
                message=f"quantized payload '{name}' is sent here but no "
                        f"matching dequantize appears on any receive leg "
                        f"in this module — the consumer reads packed "
                        f"codewords; pair every encode with a decode"))
    stats["sites_shaped"] = stats.get("sites_shaped", 0) \
        + events.get("sites_shaped", 0)
    summary["shapes"] = {"stats": stats}
    return findings


def _own_scope_walk(fndef: ast.AST):
    for st in _own_scope_stmts(fndef):
        yield from _expr_nodes(st)


def _module_has_decode(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and shapes.classify_codec(node) == "decode":
            return True
    return False


# ---------------------------------------------------------------------------
# project pass: GC040 / GC041 / GC044 over the index


def run(index, enabled: Set[str]) -> List[Finding]:
    if not ({"GC040", "GC041", "GC044"} & enabled):
        return []
    from . import rules_spmd

    out: List[Finding] = []
    for s in index.summaries:
        for site in s["shardmap"]:
            if not rules_spmd._is_real_shard_map(index, s, site):
                continue
            target = rules_spmd._resolve_wrapped(index, s, site)
            recs = site.get("in_specs") or []
            sizes = _mesh_axis_sizes(index, s, site)
            if "GC040" in enabled and "GC040" not in site["suppress"]:
                out.extend(_gc040(index, s, site, recs, sizes))
            if "GC041" in enabled and "GC041" not in site["suppress"]:
                out.extend(_gc041(index, s, site, recs, target))
            if "GC044" in enabled and "GC044" not in site["suppress"]:
                out.extend(_gc044(index, s, site, recs, sizes, target))
    return out


def _mesh_axis_sizes(index, s, site) -> Optional[Dict[str, int]]:
    if not site.get("mesh"):
        return None
    axes = index.lookup_mesh_axes(s, site["mesh"])
    sizes = index.lookup_mesh_sizes(s, site["mesh"])
    if not axes or not sizes or len(axes) != len(sizes):
        return None
    return dict(zip(axes, sizes))


def _resolved_entries(index, s, rec) -> Optional[List[Optional[List[str]]]]:
    return shapes.resolve_p_entries(
        rec, lambda sym: index.lookup_str_const(s, sym))


def _gc040(index, s, site, recs, sizes) -> List[Finding]:
    shapes_list = site.get("call_shapes")
    if not shapes_list or not sizes:
        return []
    out: List[Finding] = []
    for i, (rec, shp) in enumerate(zip(recs, shapes_list)):
        if shp is None:
            continue
        entries = _resolved_entries(index, s, rec)
        if entries is None:
            continue
        for j, axes in enumerate(entries):
            if not axes or j >= len(shp):
                continue
            dim = shapes.dim_value(
                shp[j], lambda n: index.lookup_int_const(s, n))
            if dim is None:
                continue
            if not all(a in sizes for a in axes):
                continue
            total = 1
            for a in axes:
                total *= sizes[a]
            if total > 0 and dim % total != 0:
                out.append(Finding(
                    path=s["path"], line=site["lineno"], col=1,
                    rule="GC040",
                    message=f"in_specs[{i}] shards dim {j} (size {dim}) "
                            f"over mesh ax{'is' if len(axes) == 1 else 'es'}"
                            f" {'+'.join(axes)} of total size {total}, "
                            f"which does not divide it — GSPMD silently "
                            f"pads every shard and collectives see the "
                            f"padding; make the dim divisible or reshard"))
    return out


def _logical_axis_map(index, s, rec) -> Optional[Dict[str, Any]]:
    """The LOGICAL_TO_AXES table governing a spec_for_logical record."""
    fn = rec.get("fn")
    if fn:
        fq = index.resolve(s, fn)
        mod, _rest = index._split_module(fq)
        if mod is not None:
            t = index.modules[mod].get("logical_tables", {}) \
                .get("LOGICAL_TO_AXES")
            if t is not None:
                return t
    for other in index.summaries:
        t = other.get("logical_tables", {}).get("LOGICAL_TO_AXES")
        if t is not None:
            return t
    return None


def _spec_pos_for_param(site, param_idx: int) -> Optional[int]:
    fnref = site["fn"]
    if fnref["kind"] == "partial":
        pos = param_idx - fnref["npos"]
        return pos if pos >= 0 else None
    return param_idx


def _contraction_axes(index, s, site, recs, rec_pos: int, dim: int,
                      rank_hint: Optional[int]
                      ) -> Optional[Tuple[List[str], str]]:
    """Mesh/logical axes sharding contraction position `dim` of spec
    `rec_pos`, plus a description of how the spec said so; None when
    replicated or unresolvable."""
    if rec_pos >= len(recs):
        return None
    rec = recs[rec_pos]
    kind = rec.get("kind")
    if kind == "p":
        entries = rec["entries"]
        pos = dim
        if pos < 0:
            if rank_hint is None:
                return None
            pos = rank_hint + pos
            if pos < 0:
                return None
        if pos >= len(entries):
            return None   # implicit trailing None: replicated
        resolved = _resolved_entries(index, s, rec)
        axes = resolved[pos] if resolved else None
        if axes:
            return axes, f"P(..., {'+'.join(axes)!s}, ...)"
        return None
    if kind in ("logical", "logical_ref"):
        if kind == "logical":
            logical_tuple = rec.get("axes")
        else:
            table = index.lookup_logical_table(s, rec["table"])
            logical_tuple = table.get(rec["key"]) if table else None
        if not isinstance(logical_tuple, (list, tuple)):
            return None
        pos = dim if dim >= 0 else len(logical_tuple) + dim
        if pos < 0 or pos >= len(logical_tuple):
            return None
        logical = logical_tuple[pos]
        amap = _logical_axis_map(index, s, rec)
        axes = shapes.logical_entry_axes(logical, amap)
        if axes:
            return axes, f"logical dim {logical!r}"
        return None
    return None


def _gc041(index, s, site, recs, target) -> List[Finding]:
    if target is None or not recs:
        return []
    ts, tfn = target
    contractions = (tfn.get("shapes") or {}).get("contractions", ())
    if not contractions:
        return []
    shapes_list = site.get("call_shapes") or []
    out: List[Finding] = []
    for con in contractions:
        for opnd in con["operands"]:
            rec_pos = _spec_pos_for_param(site, opnd["param"])
            if rec_pos is None:
                continue
            rank_hint = None
            if rec_pos < len(shapes_list) \
                    and shapes_list[rec_pos] is not None:
                rank_hint = len(shapes_list[rec_pos])
            for dim in opnd["dims"]:
                hit = _contraction_axes(index, s, site, recs, rec_pos,
                                        dim, rank_hint)
                if hit is None:
                    continue
                axes, how = hit
                out.append(Finding(
                    path=s["path"], line=site["lineno"], col=1,
                    rule="GC041",
                    message=f"in_specs[{rec_pos}] shards the contraction "
                            f"dim (position {dim}) of {tfn['qname']}()'s "
                            f"{con['kind']} at {ts['path']}:"
                            f"{con['lineno']} on {'+'.join(axes)} "
                            f"({how}): contracting a sharded dim "
                            f"produces per-shard partial sums — "
                            f"contraction dims never shard "
                            f"(SpecLayout rule); replicate the dim or "
                            f"psum the result"))
    return out


def _gc044(index, s, site, recs, sizes, target) -> List[Finding]:
    if target is None or not sizes:
        return []
    shapes_list = site.get("call_shapes")
    if not shapes_list:
        return []
    ts, tfn = target
    params = list(tfn["params"])
    tq = tfn["qname"]
    # per-shard shapes of the wrapped function's parameters
    pershard: Dict[str, List[Optional[int]]] = {}
    for pi, pname in enumerate(params):
        pos = _spec_pos_for_param(site, pi)
        if pos is None or pos >= len(shapes_list) \
                or shapes_list[pos] is None or pos >= len(recs):
            continue
        entries = _resolved_entries(index, s, recs[pos])
        if entries is None:
            continue
        dims: List[Optional[int]] = []
        for j, raw in enumerate(shapes_list[pos]):
            dim = shapes.dim_value(
                raw, lambda n: index.lookup_int_const(s, n))
            if dim is None:
                dims.append(None)
                continue
            axes = entries[j] if j < len(entries) else []
            if axes is None:
                dims.append(None)
                continue
            total = 1
            ok = True
            for a in axes:
                if a not in sizes:
                    ok = False
                    break
                total *= sizes[a]
            if not ok or total <= 0 or dim % total != 0:
                dims.append(None)   # GC040 territory
            else:
                dims.append(dim // total)
        pershard[pname] = dims
    if not pershard:
        return []
    out: List[Finding] = []
    for coll in ts["collectives"]:
        if coll["encl"] != tq and not coll["encl"].startswith(tq + "."):
            continue
        if coll["op"] not in ("psum_scatter", "all_to_all"):
            continue
        if "GC044" in coll["suppress"]:
            continue
        name = coll.get("arg0")
        if name not in pershard:
            continue
        ax = coll.get("axis") or {}
        lits = ax.get("lits") or []
        if len(lits) != 1 or ax.get("syms") or not ax.get("clean"):
            continue
        size = sizes.get(lits[0])
        if not size:
            continue
        if coll["op"] == "all_to_all":
            k = coll.get("split_axis") or 0
        else:
            k = 0
        dims = pershard[name]
        if k >= len(dims) or dims[k] is None:
            continue
        if dims[k] % size != 0:
            out.append(Finding(
                path=ts["path"], line=coll["lineno"], col=coll["col"],
                rule="GC044",
                message=f"{coll['op']}() splits dim {k} of '{name}' "
                        f"(per-shard size {dims[k]}) across axis "
                        f"'{lits[0]}' of size {size}, which does not "
                        f"divide it — the scatter misaligns shard "
                        f"boundaries (lowering error or silent "
                        f"padding); make the per-shard dim divisible"))
    return out


def aggregate_stats(summaries) -> Dict[str, int]:
    total: Dict[str, int] = {}
    for s in summaries:
        for k, v in (s.get("shapes") or {}).get("stats", {}).items():
            total[k] = total.get(k, 0) + int(v)
    return total
