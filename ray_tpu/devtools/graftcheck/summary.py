"""Per-file fact extraction for the whole-program engine.

One parse of a file yields a JSON-serializable *summary* — the facts the
project-level rule passes need without ever touching the AST again:

- imports (absolute + relative, resolved to dotted targets),
- function/method records: params, decorators, blocking ``get()`` sites,
  ``.remote()`` submissions (with receiver + argument provenance and
  whether the result is synchronously waited on), plain calls, returns,
  module-global writes, locally-created unserializable objects,
- class records: actor-ness, methods, ``self.x = <handle>`` bindings,
- compiled-graph ``<recv>.<method>.bind(...)`` sites with receiver
  resolution (handle var / list-of-handles loop var / self attribute),
- SPMD facts: ``shard_map`` call sites (wrapped fn, in_specs arity +
  per-entry PartitionSpec records, axis_names, mesh) — including sites
  reached through ``lower_jit``/``lower_shard_map`` wrappers and
  through ``functools.partial(shard_map, ...)`` bindings — collective
  call sites with their axis argument and operand name, module-level
  mesh/str/int constants and statically-known mesh axis *sizes*,
- the file's suppression map, so project findings honor the same
  ``# graftcheck: disable=`` comments as the local rules.

Summaries are cached by content hash (see :mod:`.engine`); the project
passes (:mod:`.rules_project`, :mod:`.rules_spmd`,
:mod:`.rules_shapes`) run over summaries only, which is what makes
warm runs cheap.

GC022 (donated-buffer read after a jitted call) moved to the CFG in
v4: :mod:`.rules_shapes` evaluates it path-sensitively at extraction
time, so its findings still ride the cache with the local ones.
"""
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .local import (Finding, _assigned_names, _ctor_kind, _dotted,
                    _is_remote_decorator, _parse_suppressions,
                    _remote_handle_class_info as _handle_class)

# Folded into the cache key (engine.CACHE_VERSION): bump when the
# summary schema or extraction logic changes.
SUMMARY_VERSION = 5  # v5: concurrency lock tables + held-call facts

#: the two sharding/lower.py wrappers that carry a program onto a mesh;
#: sites through them are recorded alongside plain shard_map sites
LOWER_WRAPPERS = ("lower_shard_map", "lower_jit")

# collective -> positional index of its axis argument
COLLECTIVE_AXIS_ARG: Dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1, "pshuffle": 1,
    "axis_index": 0, "pvary": 1, "pcast": 1,
}
_AXIS_KWARGS = ("axis_name", "axis_names")


def _dotted_str(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    d = _dotted(node)
    return ".".join(d) if d else None


def _axis_value(node: ast.AST) -> Dict[str, Any]:
    """Classify an axis argument: literal strings, symbolic names, and
    whether every element was understood (``clean``)."""
    lits: List[str] = []
    syms: List[str] = []
    clean = True

    def _one(n: ast.AST) -> None:
        nonlocal clean
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            lits.append(n.value)
        elif isinstance(n, ast.Name):
            syms.append(n.id)
        elif isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            for e in n.elts:
                _one(e)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in ("frozenset", "set", "tuple", "list") \
                and len(n.args) == 1:
            _one(n.args[0])
        else:
            clean = False

    _one(node)
    return {"lits": lits, "syms": syms, "clean": clean}


def _prov(expr: Optional[ast.AST]) -> Dict[str, Any]:
    """Provenance of a value expression, as far as one file can tell."""
    if expr is None:
        return {"kind": "none"}
    if isinstance(expr, ast.Await):
        return _prov(expr.value)
    if isinstance(expr, ast.Call):
        kind = _ctor_kind(expr)
        if kind:
            return {"kind": "ctor", "ctor": kind}
        if isinstance(expr.func, ast.Attribute) and expr.func.attr == "remote":
            return {"kind": "submit"}
        return {"kind": "call", "name": _dotted_str(expr.func) or ""}
    if isinstance(expr, ast.Name):
        return {"kind": "var", "name": expr.id}
    return {"kind": "other"}


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _spec_entry(node: ast.AST) -> Any:
    """One PartitionSpec entry -> JSON-able record: None, {"lit": axis},
    {"sym": name}, {"tup": [entries]}, or {"unk": True}."""
    if isinstance(node, ast.Constant) and node.value is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {"lit": node.value}
    if isinstance(node, ast.Name):
        return {"sym": node.id}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {"tup": [_spec_entry(e) for e in node.elts]}
    return {"unk": True}


def _logical_tuple(node: ast.AST) -> Optional[List[Optional[str]]]:
    """A literal logical-axis tuple ("batch", None, "embed") -> list."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out: List[Optional[str]] = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and (e.value is None
                                            or isinstance(e.value, str)):
            out.append(e.value)
        else:
            return None
    return out


def _spec_record(node: ast.AST) -> Dict[str, Any]:
    """One in_specs element -> a spec record the shape rules can
    resolve: a literal ``P(...)``, a ``<layout>.spec_for_logical(...)``
    call (literal tuple, or a key into a ``logical_axes()`` table that
    the project pass resolves cross-file), a symbol, or unknown."""
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d is not None and d[-1] in ("P", "PartitionSpec"):
            return {"kind": "p",
                    "entries": [_spec_entry(a) for a in node.args]}
        if d is not None and d[-1] == "spec_for_logical" and node.args:
            fn = ".".join(d)
            arg = node.args[0]
            axes = _logical_tuple(arg)
            if axes is not None:
                return {"kind": "logical", "axes": axes, "fn": fn}
            # <Model>.logical_axes()["name"] / TABLE["name"]
            if isinstance(arg, ast.Subscript) \
                    and isinstance(arg.slice, ast.Constant) \
                    and isinstance(arg.slice.value, str):
                base = arg.value
                table = None
                if isinstance(base, ast.Call):
                    table = _dotted_str(base.func)
                elif isinstance(base, (ast.Name, ast.Attribute)):
                    table = _dotted_str(base)
                if table:
                    return {"kind": "logical_ref", "table": table,
                            "key": arg.slice.value, "fn": fn}
            return {"kind": "unk"}
    if isinstance(node, (ast.Name, ast.Attribute)):
        d = _dotted_str(node)
        if d:
            return {"kind": "sym", "name": d}
    return {"kind": "unk"}


def _jit_donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """``jax.jit(f, donate_argnums=...)`` /
    ``functools.partial(jax.jit, donate_argnums=...)`` -> positions."""
    func_d = _dotted(call.func)
    if func_d is None:
        return None
    is_jit = func_d[-1] == "jit"
    is_partial_jit = False
    if func_d[-1] == "partial" and call.args:
        arg_d = _dotted(call.args[0])
        is_partial_jit = arg_d is not None and arg_d[-1] == "jit"
    if not (is_jit or is_partial_jit):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _int_tuple(kw.value)
    return None


def _partial_shardmap(value: ast.AST) -> Optional[Dict[str, Any]]:
    """``partial(shard_map, ...)`` / ``functools.partial(jax.shard_map,
    mesh=..., in_specs=...)`` -> the bound arguments, so a later
    ``fn(body)`` call can be synthesized into a shard_map site."""
    if not isinstance(value, ast.Call):
        return None
    func_d = _dotted(value.func)
    if func_d is None or func_d[-1] != "partial" or not value.args:
        return None
    inner = _dotted(value.args[0])
    if inner is None or inner[-1] != "shard_map":
        return None
    return {"callee": inner, "pos": list(value.args[1:]),
            "kw": list(value.keywords)}


def _child_defs(stmts: Sequence[ast.stmt]) -> List[ast.stmt]:
    """Function/class defs directly owned by this scope — any depth of
    control flow, but not inside other defs."""
    out: List[ast.stmt] = []
    stack: List[ast.stmt] = list(stmts)
    while stack:
        st = stack.pop(0)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            out.append(st)
            continue
        for fld in ("body", "orelse", "finalbody"):
            child = getattr(st, fld, None)
            if isinstance(child, list):
                stack.extend(c for c in child if isinstance(c, ast.stmt))
        for handler in getattr(st, "handlers", ()):
            stack.extend(handler.body)
        for case in getattr(st, "cases", ()):
            stack.extend(case.body)
    return out


def suppressed(summary: Dict[str, Any], line: int, rule: str) -> bool:
    return rule in summary.get("suppress_file", ()) \
        or rule in summary.get("suppress_line", {}).get(str(line), ())


# ---------------------------------------------------------------------------
# the extractor


class _Extractor:
    def __init__(self, path: str, source: str, tree: ast.Module,
                 module: str):
        self.path = path
        self.tree = tree
        per_line, file_wide = _parse_suppressions(source)
        self.summary: Dict[str, Any] = {
            "path": path,
            "module": module,
            "suppress_line": {str(k): sorted(v) for k, v in per_line.items()},
            "suppress_file": sorted(file_wide),
            "imports": {},
            "module_unser": {},
            "str_consts": {},
            "tuple_consts": {},
            "int_consts": {},       # module var -> int literal
            "int_tuple_consts": {},  # module var -> [int, ...]
            "mesh_vars": {},
            "mesh_shapes": {},      # mesh var -> [axis sizes] when known
            "handles": {},        # module var -> dotted class (as written)
            "handle_lists": {},   # module list-of-handles var -> class
            "functions": {},      # qname -> fn record
            "classes": {},        # name -> class record
            "bind_sites": [],
            "shardmap": [],
            "collectives": [],
            "actor_options": [],  # creation-site concurrency facts
        }
        self.extra_findings: List[Finding] = []
        self._bare_get_names: Set[str] = set()
        self._seen_submits: Set[int] = set()   # id(Call) dedup
        self._devmesh: Dict[str, List[int]] = {}  # device-mesh var shapes

    # -- imports ----------------------------------------------------------

    def _import_base(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module or ""
        parts = self.summary["module"].split(".")
        if node.level > len(parts):
            return None
        base = parts[:len(parts) - node.level]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    def _iter_statements(self):
        """Every statement in the file (imports can hide inside function
        bodies and try/if blocks) without visiting expression nodes —
        ast.walk over full trees dominates cold-run time otherwise."""
        stack: List[ast.stmt] = list(self.tree.body)
        while stack:
            st = stack.pop()
            yield st
            for fld in ("body", "orelse", "finalbody"):
                child = getattr(st, fld, None)
                if isinstance(child, list):
                    stack.extend(c for c in child
                                 if isinstance(c, ast.stmt))
            for handler in getattr(st, "handlers", ()):
                stack.extend(handler.body)
            for case in getattr(st, "cases", ()):
                stack.extend(case.body)

    def _collect_imports(self) -> None:
        imports = self.summary["imports"]
        for node in self._iter_statements():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        imports[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        imports.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    target = f"{base}.{alias.name}" if base else alias.name
                    imports[alias.asname or alias.name] = target
                    if alias.name == "get" and base.split(".")[0] in (
                            "ray_tpu", "ray"):
                        self._bare_get_names.add(alias.asname or alias.name)

    # -- module level -----------------------------------------------------

    def run(self) -> Tuple[Dict[str, Any], List[Finding]]:
        self._collect_imports()
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                self._module_assign(stmt.targets[0], stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                self._module_assign(stmt.target, stmt.value)
        # module-level executable code behaves like one implicit function
        # (drivers/examples submit + get at module scope)
        mod_fn = self._fn_record("<module>", "<module>", lineno=0, cls=None,
                                 is_remote=False)
        self._scan_scope(self.tree.body, mod_fn,
                         scope_handles=dict(self.summary["handles"]),
                         scope_lists=dict(self.summary["handle_lists"]))
        self.summary["functions"]["<module>"] = mod_fn
        for d in _child_defs(self.tree.body):
            if isinstance(d, ast.ClassDef):
                self._visit_class(d)
            else:
                self._visit_fn(d, qprefix="", cls=None)
        return self.summary, self.extra_findings

    def _module_assign(self, target: ast.AST, value: ast.AST) -> None:
        s = self.summary
        names = _assigned_names(target)
        if len(names) != 1:
            return
        name = names[0]
        kind = _ctor_kind(value)
        if kind:
            s["module_unser"][name] = kind
            return
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            s["str_consts"][name] = value.value
            return
        if isinstance(value, ast.Constant) and isinstance(value.value, int) \
                and not isinstance(value.value, bool):
            s["int_consts"][name] = value.value
            return
        if isinstance(value, (ast.Tuple, ast.List)) and value.elts \
                and all(isinstance(e, ast.Constant)
                        and isinstance(e.value, str) for e in value.elts):
            s["tuple_consts"][name] = [e.value for e in value.elts]
            return
        if isinstance(value, (ast.Tuple, ast.List)) and value.elts:
            it = _int_tuple(value)
            if it is not None:
                s["int_tuple_consts"][name] = list(it)
                return
        if isinstance(value, ast.Call):
            cls, max_conc = _handle_class(value)
            if cls:
                s["handles"][name] = cls
                s["actor_options"].append(
                    {"cls": cls, "max_concurrency": max_conc,
                     "lineno": value.lineno})
                return
            shape = self._device_shape(value)
            if shape is not None:
                self._devmesh[name] = shape
            axes = self._mesh_axes(value)
            if axes is not None:
                s["mesh_vars"][name] = axes
                sizes = self._mesh_sizes(value, len(axes))
                if sizes is not None:
                    s["mesh_shapes"][name] = sizes
                return
        cls = self._handle_list_class(value)
        if cls:
            s["handle_lists"][name] = cls

    def _mesh_axes(self, call: ast.Call) -> Optional[List[str]]:
        """Literal axis names of a ``Mesh(devs, axes)`` /
        ``...(axis_names=axes)`` construction, else None."""
        d = _dotted(call.func)
        if d is None:
            return None
        cand: Optional[ast.AST] = None
        if d[-1] == "Mesh" and len(call.args) >= 2:
            cand = call.args[1]
        for kw in call.keywords:
            if kw.arg == "axis_names":
                cand = kw.value
        if cand is None:
            return None
        v = _axis_value(cand)
        if v["clean"] and not v["syms"]:
            return v["lits"]
        if v["clean"] and not v["lits"] and len(v["syms"]) == 1:
            t = self.summary["tuple_consts"].get(v["syms"][0])
            if t is not None:
                return list(t)
        return None

    def _device_shape(self, node: ast.AST) -> Optional[List[int]]:
        """Statically-known shape of a device-array expression:
        ``mesh_utils.create_device_mesh((4, 2))`` (literal or module
        int-tuple const) or ``<...>.reshape(4, 2)``."""
        if not isinstance(node, ast.Call):
            return None
        d = _dotted(node.func)
        if d is not None and d[-1] == "create_device_mesh" and node.args:
            arg = node.args[0]
            it = _int_tuple(arg)
            if it is not None:
                return list(it)
            if isinstance(arg, ast.Name):
                t = self.summary["int_tuple_consts"].get(arg.id)
                if t is not None:
                    return list(t)
            return None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "reshape" and node.args:
            shape = _int_tuple(node.args[0]) if len(node.args) == 1 \
                else _int_tuple(ast.Tuple(elts=list(node.args)))
            return list(shape) if shape is not None else None
        return None

    def _mesh_sizes(self, call: ast.Call,
                    n_axes: int) -> Optional[List[int]]:
        """Per-axis sizes of a ``Mesh(devs, axes)`` construction when
        the device array's shape is statically known."""
        d = _dotted(call.func)
        if d is None or d[-1] != "Mesh" or not call.args:
            return None
        dev = call.args[0]
        shape = self._device_shape(dev)
        if shape is None and isinstance(dev, ast.Name):
            shape = self._devmesh.get(dev.id)
        if shape is not None and len(shape) == n_axes:
            return shape
        return None

    def _handle_list_class(self, value: ast.AST) -> Optional[str]:
        """``[Cls.remote(...) for ...]`` / ``[Cls.remote(), ...]`` ->
        dotted class name when every element is a handle of one class."""
        elts: List[ast.AST] = []
        if isinstance(value, (ast.ListComp, ast.GeneratorExp)):
            elts = [value.elt]
        elif isinstance(value, (ast.List, ast.Tuple)) and value.elts:
            elts = list(value.elts)
        classes = set()
        for e in elts:
            if not isinstance(e, ast.Call):
                return None
            cls, _ = _handle_class(e)
            if cls is None:
                return None
            classes.add(cls)
        return classes.pop() if len(classes) == 1 else None

    # -- defs --------------------------------------------------------------

    def _visit_class(self, node: ast.ClassDef) -> None:
        is_actor = any(_is_remote_decorator(d) for d in node.decorator_list)
        rec = {
            "lineno": node.lineno,
            "is_actor": is_actor,
            "methods": [],
            "attr_handles": {},   # self.<attr> -> dotted class
            "has_async": False,
        }
        self.summary["classes"].setdefault(node.name, rec)
        for d in _child_defs(node.body):
            if isinstance(d, ast.ClassDef):
                self._visit_class(d)
            else:
                rec["methods"].append(d.name)
                if isinstance(d, ast.AsyncFunctionDef):
                    rec["has_async"] = True
                self._visit_fn(d, qprefix=node.name + ".", cls=node.name)

    def _fn_record(self, name: str, qname: str, lineno: int,
                   cls: Optional[str], is_remote: bool) -> Dict[str, Any]:
        return {
            "name": name, "qname": qname, "lineno": lineno, "cls": cls,
            "is_remote": is_remote, "params": [], "n_defaults": 0,
            "has_vararg": False, "annotations": {},
            "gets": [], "submits": [], "calls": [], "returns": [],
            "global_writes": [], "local_unser": {}, "call_assigns": {},
        }

    def _visit_fn(self, node: ast.AST, qprefix: str,
                  cls: Optional[str]) -> None:
        qname = qprefix + node.name
        cls_rec = self.summary["classes"].get(cls) if cls else None
        is_remote = any(_is_remote_decorator(d)
                        for d in node.decorator_list) \
            or bool(cls_rec and cls_rec["is_actor"])
        fn = self._fn_record(node.name, qname, node.lineno, cls, is_remote)
        args = node.args
        pos = list(args.posonlyargs) + list(args.args)
        fn["params"] = [a.arg for a in pos]
        fn["n_defaults"] = len(args.defaults)
        fn["has_vararg"] = args.vararg is not None
        for a in pos + list(args.kwonlyargs):
            ann = a.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                fn["annotations"][a.arg] = ann.value
            elif ann is not None:
                d = _dotted_str(ann)
                if d:
                    fn["annotations"][a.arg] = d

        scope_handles: Dict[str, str] = dict(self.summary["handles"])
        scope_lists: Dict[str, str] = dict(self.summary["handle_lists"])
        # annotated params act as handles of the annotated class (only
        # CamelCase annotations can be actor classes)
        for p, ann in fn["annotations"].items():
            if ann.split(".")[-1][:1].isupper():
                scope_handles.setdefault(p, ann)

        self._scan_scope(node.body, fn, scope_handles, scope_lists)
        self.summary["functions"][qname] = fn
        for d in _child_defs(node.body):
            if isinstance(d, ast.ClassDef):
                self._visit_class(d)
            else:
                self._visit_fn(d, qprefix=qname + ".", cls=cls)

    # -- one-scope statement scan -----------------------------------------

    def _scan_scope(self, stmts: Sequence[ast.stmt], fn: Dict[str, Any],
                    scope_handles: Dict[str, str],
                    scope_lists: Dict[str, str]) -> None:
        stores: Dict[str, List[int]] = {}
        globals_declared: Set[str] = set()
        ctx = {"fn": fn, "handles": scope_handles, "lists": scope_lists,
               "stores": stores, "sm_partials": {}}

        def walk_stmt(stmt: ast.stmt) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(stmt, ast.Global):
                globals_declared.update(stmt.names)
            if isinstance(stmt, ast.Assign):
                self._scan_assign(stmt, ctx)
            if isinstance(stmt, ast.For) and isinstance(stmt.iter, ast.Name):
                lcls = scope_lists.get(stmt.iter.id)
                if lcls:
                    for nm in _assigned_names(stmt.target):
                        scope_handles[nm] = lcls
            if isinstance(stmt, ast.Return):
                p = _prov(stmt.value)
                p["lineno"] = stmt.lineno
                fn["returns"].append(p)
            for node in ast.iter_child_nodes(stmt):
                if not isinstance(node, (ast.stmt, ast.ExceptHandler)):
                    self._scan_expr_tree(node, stmt, ctx)
            for fld in ("body", "orelse", "finalbody"):
                child = getattr(stmt, fld, None)
                if isinstance(child, list):
                    for c in child:
                        if isinstance(c, ast.stmt):
                            walk_stmt(c)
            for handler in getattr(stmt, "handlers", ()):
                for c in handler.body:
                    walk_stmt(c)
            for case in getattr(stmt, "cases", ()):
                for c in case.body:
                    walk_stmt(c)

        for stmt in stmts:
            walk_stmt(stmt)

        fn["global_writes"] = sorted(globals_declared & set(stores))

        # sync-marking: a get() over a var holding a submit result
        for g in fn["gets"]:
            for var in g.get("vars", ()):
                for sub in fn["submits"]:
                    if var in sub["assigned"] and sub["lineno"] <= g["lineno"]:
                        sub["sync"] = True
                        sub["sync_line"] = g["lineno"]
                        g["matched"] = True
        # `ref.get()`-style maybe-gets only count when matched to a submit
        fn["gets"] = [g for g in fn["gets"]
                      if not g.get("maybe") or g.get("matched")]

    def _scan_assign(self, stmt: ast.Assign, ctx: Dict[str, Any]) -> None:
        fn = ctx["fn"]
        value = stmt.value
        names = _assigned_names(stmt.targets[0]) if len(stmt.targets) == 1 \
            else []
        if len(names) == 1:
            name = names[0]
            kind = _ctor_kind(value)
            if kind:
                fn["local_unser"][name] = kind
            if isinstance(value, ast.Call):
                cls, max_conc = _handle_class(value)
                if cls:
                    ctx["handles"][name] = cls
                    self.summary["actor_options"].append(
                        {"cls": cls, "max_concurrency": max_conc,
                         "lineno": value.lineno})
                part = _partial_shardmap(value)
                if part is not None:
                    ctx["sm_partials"][name] = part
                if not kind and not cls:
                    callee = _dotted_str(value.func)
                    if callee:
                        fn["call_assigns"][name] = callee
            lcls = self._handle_list_class(value)
            if lcls:
                ctx["lists"][name] = lcls
            return
        if len(stmt.targets) == 1 and isinstance(stmt.targets[0],
                                                 ast.Attribute):
            tgt = stmt.targets[0]
            # self.<attr> = <handle>: class-level attr handle table
            if isinstance(tgt.value, ast.Name) and tgt.value.id == "self" \
                    and fn["cls"]:
                cls_rec = self.summary["classes"].get(fn["cls"])
                if cls_rec is not None:
                    hcls = None
                    if isinstance(value, ast.Call):
                        hcls, _ = _handle_class(value)
                    if hcls is None and isinstance(value, ast.Name):
                        hcls = ctx["handles"].get(value.id)
                    if hcls:
                        cls_rec["attr_handles"][tgt.attr] = hcls

    # -- expression scan ---------------------------------------------------

    def _scan_expr_tree(self, root: ast.AST, stmt: ast.stmt,
                        ctx: Dict[str, Any]) -> None:
        stores = ctx["stores"]
        stack: List[ast.AST] = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Name):
                if not isinstance(node.ctx, ast.Load):
                    stores.setdefault(node.id, []).append(node.lineno)
            elif isinstance(node, ast.Call):
                self._scan_call(node, stmt, ctx)
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
                    stack.append(child)

    def _line_suppressions(self, line: int) -> List[str]:
        out = list(self.summary["suppress_line"].get(str(line), ()))
        out.extend(self.summary["suppress_file"])
        return out

    def _scan_call(self, call: ast.Call, stmt: ast.stmt,
                   ctx: Dict[str, Any]) -> None:
        fn = ctx["fn"]
        func = call.func
        d = _dotted(func)

        get_rec = self._blocking_get(call)
        if get_rec is not None:
            fn["gets"].append(get_rec)
            # an inline submit inside the get is synchronous immediately
            for sub in ast.walk(call):
                if isinstance(sub, ast.Call) and sub is not call \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "remote":
                    rec = self._submit_record(sub, stmt, ctx)
                    if rec is not None:
                        rec["sync"] = True
                        rec["sync_line"] = call.lineno
            return

        if isinstance(func, ast.Attribute) and func.attr == "remote":
            self._submit_record(call, stmt, ctx)
            return

        if isinstance(func, ast.Attribute) and func.attr == "bind" \
                and isinstance(func.value, ast.Attribute):
            self._bind_site(call, ctx)

        if d is not None and d[-1] == "shard_map":
            self._shardmap_site(call, d, fn)
        elif d is not None and d[-1] in LOWER_WRAPPERS:
            self._shardmap_site(call, d, fn, wrapper=d[-1])
        elif d is not None and len(d) == 1 and d[0] in ctx["sm_partials"]:
            # ``fn = partial(shard_map, body, ...); fn(...)`` — synthesize
            # a site from the bound arguments merged with the call's own
            part = ctx["sm_partials"][d[0]]
            merged = ast.Call(func=call.func,
                              args=list(part["pos"]) + list(call.args),
                              keywords=list(part["kw"]) + list(call.keywords))
            ast.copy_location(merged, call)
            self._shardmap_site(merged, part["callee"], fn)

        if d is not None and d[-1] in COLLECTIVE_AXIS_ARG \
                and (len(d) == 1 or "lax" in d):
            self._collective_site(call, d, fn)

        if d is not None and d[-1] not in ("remote", "bind", "options",
                                           "get"):
            fn["calls"].append({
                "lineno": call.lineno, "col": call.col_offset + 1,
                "name": ".".join(d),
                "args": [_prov(a) for a in call.args],
                "suppress": self._line_suppressions(call.lineno)})

    def _blocking_get(self, call: ast.Call) -> Optional[Dict[str, Any]]:
        """A blocking-get record, or None. ``maybe`` marks ``ref.get()``
        forms that only count once matched to a submit in this scope
        (``d.get(...)`` on dicts must stay silent)."""
        func = call.func
        maybe = False
        args: Sequence[ast.AST] = call.args
        if isinstance(func, ast.Attribute) and func.attr == "get":
            recv = func.value
            dd = _dotted(recv)
            if dd in (("ray_tpu",), ("ray",)):
                pass
            elif isinstance(recv, ast.Call):
                inner = _dotted(recv.func)
                if inner is not None and inner[-1] == "get_runtime":
                    pass
                elif isinstance(recv.func, ast.Attribute) \
                        and recv.func.attr == "remote":
                    args = ()  # f.remote().get(): inline-marked
                else:
                    return None
            elif isinstance(recv, ast.Name) and not call.args:
                maybe = True
                args = (recv,)
            else:
                return None
        elif isinstance(func, ast.Name) and func.id in self._bare_get_names:
            pass
        else:
            return None
        out: List[str] = []
        for a in list(args)[:1]:
            if isinstance(a, ast.Name):
                out.append(a.id)
            elif isinstance(a, (ast.List, ast.Tuple)):
                out.extend(e.id for e in a.elts if isinstance(e, ast.Name))
        return {"lineno": call.lineno, "col": call.col_offset + 1,
                "vars": out, "maybe": maybe,
                "suppress": self._line_suppressions(call.lineno)}

    def _submit_record(self, call: ast.Call, stmt: ast.stmt,
                       ctx: Dict[str, Any]) -> Optional[dict]:
        if id(call) in self._seen_submits:
            return None
        fn = ctx["fn"]
        base = call.func.value
        rec: Dict[str, Any] = {
            "lineno": call.lineno, "col": call.col_offset + 1,
            "sync": False, "sync_line": None, "assigned": [],
            "args": [_prov(a) for a in call.args],
            "kwargs": {kw.arg: _prov(kw.value) for kw in call.keywords
                       if kw.arg},
            "suppress": self._line_suppressions(call.lineno),
        }
        cls, max_conc = _handle_class(call)
        if cls is None and isinstance(base, ast.Call) \
                and isinstance(base.func, ast.Attribute) \
                and base.func.attr == "options" \
                and isinstance(base.func.value, ast.Attribute):
            # h.m.options(num_returns=..., ...).remote(): a method-level
            # options wrapper — the submit edge is the same h.m edge the
            # bare spelling produces (the direct-dispatch transport
            # doesn't change the call graph, and GC010 must see these
            # edges too)
            base = base.func.value
        if cls is not None:
            # creation site (Cls.remote / Cls.options(...).remote) OR a
            # plain remote-function submit spelled mod.f — the project
            # pass disambiguates by what the name resolves to
            rec.update({"form": "func", "name": cls,
                        "options": {"max_concurrency": max_conc}})
        elif isinstance(base, ast.Name):
            rec.update({"form": "func", "name": base.id, "options": None})
        elif isinstance(base, ast.Attribute):
            hroot = base.value
            if isinstance(hroot, ast.Name) and hroot.id == "self":
                # self.m.remote(): a task submitted to our own handle is
                # not expressible this way in the API; treat the method
                # name as a same-class target (current_actor() pattern)
                rec.update({"form": "method", "method": base.attr,
                            "recv": {"kind": "self", "cls": fn["cls"]}})
            elif isinstance(hroot, ast.Name):
                rec.update({"form": "method", "method": base.attr,
                            "recv": {"kind": "name", "name": hroot.id,
                                     "cls": ctx["handles"].get(hroot.id)}})
            elif isinstance(hroot, ast.Attribute) \
                    and isinstance(hroot.value, ast.Name) \
                    and hroot.value.id == "self":
                rec.update({"form": "method", "method": base.attr,
                            "recv": {"kind": "selfattr", "attr": hroot.attr,
                                     "cls": None}})
            elif isinstance(hroot, ast.Subscript) \
                    and isinstance(hroot.value, ast.Name):
                rec.update({"form": "method", "method": base.attr,
                            "recv": {"kind": "name",
                                     "name": hroot.value.id,
                                     "cls": ctx["lists"].get(
                                         hroot.value.id)}})
            else:
                rec.update({"form": "method", "method": base.attr,
                            "recv": {"kind": "other", "cls": None}})
        else:
            return None
        if isinstance(stmt, ast.Assign) and stmt.value is call:
            for t in stmt.targets:
                rec["assigned"].extend(_assigned_names(t))
        self._seen_submits.add(id(call))
        fn["submits"].append(rec)
        return rec

    def _bind_site(self, call: ast.Call, ctx: Dict[str, Any]) -> None:
        fn = ctx["fn"]
        method_ref = call.func.value          # <recv>.<method>
        recv = method_ref.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            # `self.X.bind(...)`: X is an instance attribute (sockets,
            # listeners), not an actor-method node — a cgraph self-bind
            # spells `self.<handle>.<method>.bind(...)` (3 levels)
            return
        site: Dict[str, Any] = {
            "lineno": call.lineno, "method": method_ref.attr,
            "cls": None, "resolved": False, "cls_ctx": fn["cls"],
        }
        if isinstance(recv, ast.Name):
            cls = ctx["handles"].get(recv.id)
            if cls:
                site.update({"cls": cls, "resolved": True})
        elif isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and fn["cls"]:
            cls_rec = self.summary["classes"].get(fn["cls"])
            cls = cls_rec["attr_handles"].get(recv.attr) if cls_rec else None
            if cls:
                site.update({"cls": cls, "resolved": True})
        elif isinstance(recv, ast.Subscript) \
                and isinstance(recv.value, ast.Name):
            cls = ctx["lists"].get(recv.value.id)
            if cls:
                site.update({"cls": cls, "resolved": True})
        self.summary["bind_sites"].append(site)

    def _shardmap_site(self, call: ast.Call, d: Tuple[str, ...],
                       fn: Dict[str, Any], wrapper: str = "shard_map",
                       ) -> None:
        site: Dict[str, Any] = {
            "lineno": call.lineno, "callee": ".".join(d),
            "encl": fn["qname"], "fn": {"kind": "other"},
            "in_specs_arity": None, "axis_given": False,
            "axis": None, "mesh": None, "wrapper": wrapper,
            "in_specs": None, "out_specs": None,
            "suppress": self._line_suppressions(call.lineno),
        }
        pos = list(call.args)
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        fn_expr = pos[0] if pos else None
        if isinstance(fn_expr, (ast.Name, ast.Attribute)):
            site["fn"] = {"kind": "name", "name": _dotted_str(fn_expr) or ""}
        elif isinstance(fn_expr, ast.Lambda):
            a = fn_expr.args
            site["fn"] = {"kind": "lambda",
                          "nparams": len(a.posonlyargs) + len(a.args),
                          "ndefaults": len(a.defaults),
                          "vararg": a.vararg is not None}
        elif isinstance(fn_expr, ast.Call):
            fd = _dotted(fn_expr.func)
            if fd is not None and fd[-1] == "partial" and fn_expr.args:
                site["fn"] = {"kind": "partial",
                              "name": _dotted_str(fn_expr.args[0]) or "",
                              "npos": len(fn_expr.args) - 1,
                              "kw": [k.arg for k in fn_expr.keywords
                                     if k.arg]}
        if wrapper == "shard_map":
            mesh_expr = kw.get("mesh") or (pos[1] if len(pos) > 1 else None)
            specs = kw.get("in_specs") if "in_specs" in kw \
                else (pos[2] if len(pos) > 2 else None)
        else:
            # lower_shard_map(fn, owner, *, in_specs=..., out_specs=...)
            # and lower_jit share the slot layout; specs are keyword-only.
            mesh_expr = pos[1] if len(pos) > 1 else None
            specs = kw.get("in_specs")
        site["mesh"] = _dotted_str(mesh_expr) if mesh_expr is not None \
            else None
        if isinstance(specs, (ast.Tuple, ast.List)):
            site["in_specs_arity"] = len(specs.elts)
            site["in_specs"] = [_spec_record(e) for e in specs.elts]
        elif specs is not None:
            site["in_specs"] = [_spec_record(specs)]
        out = kw.get("out_specs") if "out_specs" in kw \
            else (pos[3] if wrapper == "shard_map" and len(pos) > 3
                  else None)
        if isinstance(out, (ast.Tuple, ast.List)):
            site["out_specs"] = [_spec_record(e) for e in out.elts]
        elif out is not None:
            site["out_specs"] = [_spec_record(out)]
        ax = kw.get("axis_names")
        if ax is not None:
            site["axis_given"] = True
            site["axis"] = _axis_value(ax)
        self.summary["shardmap"].append(site)

    def _collective_site(self, call: ast.Call, d: Tuple[str, ...],
                         fn: Dict[str, Any]) -> None:
        op = d[-1]
        idx = COLLECTIVE_AXIS_ARG[op]
        ax_expr: Optional[ast.AST] = None
        if idx < len(call.args):
            ax_expr = call.args[idx]
        for k in call.keywords:
            if k.arg in _AXIS_KWARGS:
                ax_expr = k.value
        arg0 = None
        if call.args and isinstance(call.args[0], ast.Name):
            arg0 = call.args[0].id
        split_axis = None
        for k in call.keywords:
            if k.arg == "split_axis" and isinstance(k.value, ast.Constant) \
                    and isinstance(k.value.value, int):
                split_axis = k.value.value
        if split_axis is None and op == "all_to_all" and len(call.args) > 2 \
                and isinstance(call.args[2], ast.Constant) \
                and isinstance(call.args[2].value, int):
            split_axis = call.args[2].value
        self.summary["collectives"].append({
            "lineno": call.lineno, "col": call.col_offset + 1,
            "op": op, "dotted": ".".join(d),
            "axis": _axis_value(ax_expr) if ax_expr is not None else None,
            "encl": fn["qname"], "arg0": arg0, "split_axis": split_axis,
            "suppress": self._line_suppressions(call.lineno)})


def extract(path: str, source: str, tree: ast.Module,
            module: str) -> Tuple[Dict[str, Any], List[Finding]]:
    """Parse-once fact extraction: returns (summary, findings from
    extraction-time local rules — none today; the CFG passes in
    :mod:`.rules_lifecycle` and :mod:`.rules_shapes` contribute
    theirs through ``analyze_module``)."""
    ex = _Extractor(path, source, tree, module)
    return ex.run()
