"""GC050-GC054 — thread-aware static concurrency analysis (graftcheck v5).

Every serious latent bug this tree has shipped was a thread-safety race
in the dispatch/runtime layer, and each was caught only dynamically (the
``RAY_TPU_DEBUG_LOCKS=1`` order graph, a live smoke). This pass encodes
the same invariants statically: a held-lock MUST-state
(:class:`.dataflow.LockState`) threaded through the v3 CFG, per-class
guarded-by inference, and a project-wide lock-order graph riding the v2
call machinery.

====== =================================================================
GC050  guarded-by violation — a class attribute whose accesses majority-
       hold one specific lock is read/written on a path holding no lock
       at all (the ``_entry_for`` stale-read class)
GC051  lock-reentry hazard — a stored callback/handler invoked while a
       lock is held (the peer-connect deadlock class), a non-reentrant
       lock re-acquired while already held, or a call to a method that
       transitively re-acquires a held non-reentrant lock
GC052  lock-order cycle — the static role-level acquisition-order graph
       (nested held states + transitive acquires through resolvable
       calls) contains a strongly-connected component: the AB/BA
       deadlock precondition, reported with every hop's site
GC053  blocking call under lock — ``get()`` / ``.recv()`` /
       ``Event.wait()`` with no timeout / ``Thread.join()`` /
       ``Queue.get()`` reached while any lock is held (one slow peer
       wedges every thread queued on the lock)
GC054  non-atomic check-then-act — an ``Event.is_set()`` / dict-
       membership / attr-``None`` test whose mutating counterpart runs
       on a path where the guard lock was released in between (the
       ``NodeAgent.shutdown`` claim class)
====== =================================================================

Condition sensitivity / exemptions (what keeps the shipped tree clean):

- ``with lock:`` enters/exits track heldness exactly (finally-duplicated
  CFG edges release on every continuation, exceptions included);
- try-acquire probes: ``if lock.acquire(blocking=False):`` and the bound
  form ``got = lock.acquire(False)`` refine heldness per branch via the
  CFG's held/unheld + some/none assume labels;
- ``lock.locked()`` tests/asserts establish the caller-held invariant on
  the true path;
- RLocks (``instrumented_lock(..., reentrant=True)`` / ``RLock()``)
  nest: heldness is a depth-capped multiset and GC051 skips them;
- ``Condition(lock)`` aliases to its underlying lock, and its ``wait()``
  exempts that lock (wait releases it) in GC053;
- constructor escape: dunder methods (``__init__`` before threads exist,
  ``__repr__`` debug surfaces) neither count toward nor get flagged by
  guarded-by inference;
- attributes never written outside dunders, lock/event/queue attributes
  themselves, and typed composition attributes (``self._gcs =
  GCSClient()``) are not guard-inference candidates;
- one level of intraclass helpers: a private method whose every
  intraclass call site holds lock L is re-analyzed as entered-with-L.

Facts exported into the cached file summaries (``summary["concurrency"]``
+ per-function ``concurrency`` records) feed the project pass: GC051's
transitive-reacquire resolution and the GC052 order graph, which is also
the static half of the ``scripts/locks_gate.py`` cross-check — the
dynamic role-order graph observed under ``RAY_TPU_DEBUG_LOCKS=1`` must
be a subgraph of :func:`build_lock_order_graph`'s output.
"""
from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import dataflow
from .cfg import (FOR_BIND, STMT, TEST, WITH_ENTER, WITH_EXIT, CFGTooLarge,
                  build_cfg, is_generator)
from .dataflow import LockState
from .local import Finding, _assigned_names, _dotted, _is_lockish, \
    _iter_own_exprs
from .rules_lifecycle import _own_scope_stmts, _walk_expr, \
    collect_functions
from .summary import suppressed

CONCURRENCY_RULES: Set[str] = {"GC050", "GC051", "GC052", "GC053", "GC054"}

# -- lock / sync-object discovery -------------------------------------------

# threading-module constructors (bare or dotted through threading/
# multiprocessing; asyncio's cooperative locks are a different hazard
# domain and are deliberately NOT tracked here)
_LOCK_KINDS = {"Lock": ("lock", False), "RLock": ("rlock", True)}
_SYNC_KINDS = {
    "Event": "event", "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore", "Barrier": "semaphore",
    "Queue": "queue", "SimpleQueue": "queue", "LifoQueue": "queue",
    "PriorityQueue": "queue", "deque": "deque", "local": "tls",
    "Thread": "thread", "Timer": "thread", "Process": "thread",
    "ThreadPoolExecutor": "pool", "ProcessPoolExecutor": "pool",
}
_SYNC_BASES = {"threading", "multiprocessing", "queue", "collections",
               "concurrent", "futures", "mp"}

_MUTATORS = {"append", "appendleft", "add", "pop", "popleft", "popitem",
             "update", "setdefault", "clear", "remove", "discard",
             "extend", "insert", "push"}

_CB_ATTR_RE = re.compile(r"^_?on_[a-z0-9_]+$")
_CB_SUFFIX_RE = re.compile(r".*(_cb|_callback|_hook|_handler)$")
_CB_CONTAINER_RE = re.compile(
    r".*(callback|handler|hook|listener|subscriber|watcher)s$")
_THREADISH_RE = re.compile(r".*(thread|proc)")


def _role_of(arg: ast.AST) -> Optional[str]:
    """The role literal of an instrumented_lock() call; f-string roles
    keep their constant parts with ``*`` for each formatted hole
    (``f"refcounter.s{i}"`` -> ``refcounter.s*``)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _lock_ctor(value: ast.AST) -> Optional[Dict[str, Any]]:
    """Classify a lock-constructing RHS, or None.

    Returns ``{"kind", "reentrant", "role", "cond_of"}`` where
    ``cond_of`` is the dotted lock a ``Condition(lock)`` wraps.
    """
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func)
    if d is None:
        return None
    if len(d) > 1 and d[0] == "asyncio":
        return None
    last = d[-1]
    if last == "instrumented_lock":
        role = _role_of(value.args[0]) if value.args else None
        reentrant = any(kw.arg == "reentrant"
                        and isinstance(kw.value, ast.Constant)
                        and bool(kw.value.value)
                        for kw in value.keywords)
        return {"kind": "rlock" if reentrant else "lock",
                "reentrant": reentrant, "role": role, "cond_of": None}
    if last in _LOCK_KINDS and (len(d) == 1 or d[0] in _SYNC_BASES):
        kind, reentrant = _LOCK_KINDS[last]
        return {"kind": kind, "reentrant": reentrant, "role": None,
                "cond_of": None}
    if last == "Condition" and (len(d) == 1 or d[0] in _SYNC_BASES):
        cond_of = None
        if value.args:
            cd = _dotted(value.args[0])
            if cd is not None:
                cond_of = ".".join(cd)
        return {"kind": "condition", "reentrant": True, "role": None,
                "cond_of": cond_of}
    if last == "field":
        # dataclass field(default_factory=lambda: instrumented_lock(...))
        for kw in value.keywords:
            if kw.arg == "default_factory" \
                    and isinstance(kw.value, ast.Lambda):
                return _lock_ctor(kw.value.body)
    return None


def _sync_ctor(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func)
    if d is None:
        return None
    if len(d) > 1 and d[0] == "asyncio":
        return None
    last = d[-1]
    if last in _SYNC_KINDS and (len(d) == 1 or d[0] in _SYNC_BASES
                                or "pool" in last.lower()):
        return _SYNC_KINDS[last]
    return None


def _ctor_class(value: ast.AST) -> Optional[str]:
    """Dotted class name of a plain-composition ctor RHS (CamelCase
    final component), for the attr-type table."""
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func)
    if d is None:
        return None
    last = d[-1].lstrip("_")
    if last[:1].isupper() and d[-1] not in _LOCK_KINDS \
            and d[-1] not in _SYNC_KINDS:
        return ".".join(d)
    return None


class _ModuleLocks:
    """Every lock / sync object / typed composition attr of one module."""

    def __init__(self) -> None:
        # cls -> attr -> {"kind","reentrant","role","line","alias"}
        self.classes: Dict[str, Dict[str, Dict[str, Any]]] = {}
        # cls -> attr -> sync kind ("event"/"queue"/"thread"/...)
        self.sync: Dict[str, Dict[str, str]] = {}
        # cls -> attr -> dotted ctor class (composition typing)
        self.attr_types: Dict[str, Dict[str, str]] = {}
        self.globals_: Dict[str, Dict[str, Any]] = {}
        self.global_sync: Dict[str, str] = {}
        # cls -> attr -> ELEMENT class of a container attr (Dict value /
        # List elem annotation, or comprehension-of-ctor RHS): types
        # locals bound from lookups, so ``rec = self._actors.get(aid);
        # with rec.lock:`` resolves to the record class's lock
        self.attr_value_types: Dict[str, Dict[str, str]] = {}
        # cls -> method -> returned class (from the return annotation)
        self.method_returns: Dict[str, Dict[str, str]] = {}
        # raw annotation ASTs, resolved once the whole module is known
        self._raw_elem: Dict[str, Dict[str, ast.AST]] = {}
        self._raw_elem_ctor: Dict[str, Dict[str, str]] = {}
        self._raw_ret: Dict[str, Dict[str, ast.AST]] = {}

    def class_locks(self, cls: Optional[str]) -> Dict[str, Dict[str, Any]]:
        return self.classes.get(cls, {}) if cls else {}


def _ann_class_name(ann: ast.AST) -> Optional[str]:
    """Bare class name of a plain (or forward-string) annotation."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value.strip(), mode="eval").body
        except SyntaxError:
            return None
    d = _dotted(ann)
    return d[-1] if d else None


_DICT_ANNS = ("Dict", "dict", "Mapping", "MutableMapping", "DefaultDict",
              "defaultdict", "OrderedDict")
_SEQ_ANNS = ("List", "list", "Set", "set", "FrozenSet", "frozenset",
             "Sequence", "Iterable", "Deque", "deque", "Optional",
             "Tuple", "tuple")


def _ann_value_class(ann: ast.AST) -> Optional[str]:
    """Element/value class of a container annotation: ``Dict[K, V]`` ->
    V, ``List[X]``/``Optional[X]`` -> X, plain/forward ``X`` -> X."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value.strip(), mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value)
        if base is None:
            return None
        sl = ann.slice
        if base[-1] in _DICT_ANNS:
            if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                return _ann_class_name(sl.elts[1])
            return None
        if base[-1] in _SEQ_ANNS:
            elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            return _ann_class_name(elts[0]) if elts else None
        return None
    return _ann_class_name(ann)


def _discover(tree: ast.Module) -> _ModuleLocks:
    ml = _ModuleLocks()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            rec = _lock_ctor(stmt.value)
            if rec is not None:
                rec["line"] = stmt.lineno
                ml.globals_[name] = rec
                continue
            sk = _sync_ctor(stmt.value)
            if sk is not None:
                ml.global_sync[name] = sk
        if isinstance(stmt, ast.ClassDef):
            _discover_class(stmt, ml)
    _resolve_aliases(ml)
    # element/return types resolve only against lock-bearing classes of
    # THIS module (definition order doesn't matter: resolution is here,
    # after every class is known)
    for cls, anns in ml._raw_elem.items():
        for attr, ann in anns.items():
            v = _ann_value_class(ann)
            if v and v in ml.classes:
                ml.attr_value_types.setdefault(cls, {})[attr] = v
    for cls, ctors in ml._raw_elem_ctor.items():
        for attr, v in ctors.items():
            if v in ml.classes:
                ml.attr_value_types.setdefault(cls, {}).setdefault(attr, v)
    for cls, rets in ml._raw_ret.items():
        for meth, ann in rets.items():
            v = _ann_value_class(ann)
            if v and v in ml.classes:
                ml.method_returns.setdefault(cls, {})[meth] = v
    return ml


def _discover_class(cdef: ast.ClassDef, ml: _ModuleLocks) -> None:
    locks: Dict[str, Dict[str, Any]] = {}
    sync: Dict[str, str] = {}
    types: Dict[str, str] = {}

    def note(attr: str, value: ast.AST, line: int) -> None:
        rec = _lock_ctor(value)
        if rec is not None:
            rec["line"] = line
            locks[attr] = rec
            return
        sk = _sync_ctor(value)
        if sk is not None:
            sync.setdefault(attr, sk)
            return
        cc = _ctor_class(value)
        if cc is not None:
            types.setdefault(attr, cc)

    def note_elem(attr: str, value: ast.AST) -> None:
        # comprehension-of-ctor RHS types the container's elements
        # (``self._oshards = [_ObjShard(i) for i in range(16)]``)
        if isinstance(value, (ast.ListComp, ast.SetComp)) \
                and isinstance(value.elt, ast.Call):
            cc = _ctor_class(value.elt)
            if cc is not None:
                ml._raw_elem_ctor.setdefault(cdef.name, {})[attr] = \
                    cc.split(".")[-1]

    for stmt in cdef.body:
        # class-body defaults (incl. dataclass field(default_factory=..))
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            note(stmt.targets[0].id, stmt.value, stmt.lineno)
            note_elem(stmt.targets[0].id, stmt.value)
        if isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            if stmt.value is not None:
                note(stmt.target.id, stmt.value, stmt.lineno)
            ml._raw_elem.setdefault(cdef.name, {})[stmt.target.id] = \
                stmt.annotation
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if stmt.returns is not None:
                ml._raw_ret.setdefault(cdef.name, {})[stmt.name] = \
                    stmt.returns
            for s in _own_scope_stmts(stmt):
                if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                        and isinstance(s.targets[0], ast.Attribute) \
                        and isinstance(s.targets[0].value, ast.Name) \
                        and s.targets[0].value.id == "self":
                    note(s.targets[0].attr, s.value, s.lineno)
                    note_elem(s.targets[0].attr, s.value)
                if isinstance(s, ast.AnnAssign) \
                        and isinstance(s.target, ast.Attribute) \
                        and isinstance(s.target.value, ast.Name) \
                        and s.target.value.id == "self":
                    if s.value is not None:
                        note(s.target.attr, s.value, s.lineno)
                    ml._raw_elem.setdefault(cdef.name, {})[
                        s.target.attr] = s.annotation
        if isinstance(stmt, ast.ClassDef):
            _discover_class(stmt, ml)
    if locks:
        ml.classes[cdef.name] = locks
    if sync:
        ml.sync[cdef.name] = sync
    if types:
        ml.attr_types[cdef.name] = types


def _resolve_aliases(ml: _ModuleLocks) -> None:
    """``self._cv = Condition(self._lock)`` -> _cv aliases _lock: holding
    the condition IS holding the lock, so both share one token."""
    for locks in ml.classes.values():
        for attr, rec in locks.items():
            rec["alias"] = None
            cond_of = rec.get("cond_of")
            if rec["kind"] == "condition" and cond_of \
                    and cond_of.startswith("self."):
                tgt = cond_of[5:]
                if tgt in locks and locks[tgt]["kind"] != "condition":
                    rec["alias"] = tgt


# -- tokens -----------------------------------------------------------------
#
# A token names one lock inside one function: "self.<attr>" for class
# locks (alias-resolved: a Condition's token is its underlying lock's),
# a bare name for module-global locks, or the dotted receiver text for
# fallback lockish receivers (parameters named *lock* etc. — tracked
# for "any lock held" rules, excluded from roles and guard inference).


def _local_record_types(fndef: ast.AST, cls: Optional[str],
                        ml: _ModuleLocks) -> Dict[str, str]:
    """Local name -> lock-bearing record class, inferred from lookups on
    typed container attrs (``rec = self._actors.get(aid)``, subscripts,
    iteration — incl. through list()/sorted()), typed self-method calls
    (``sh = self._oshard(oid)``) and direct ctor binds."""
    out: Dict[str, str] = {}
    if not cls:
        return out
    vt = ml.attr_value_types.get(cls, {})
    mr = ml.method_returns.get(cls, {})
    if not vt and not mr and not ml.classes:
        return out

    def self_attr(expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if d and len(d) == 2 and d[0] == "self":
            return d[1]
        return None

    def src_class(value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Subscript):
            attr = self_attr(value.value)
            return vt.get(attr) if attr else None
        if isinstance(value, ast.Call):
            d = _dotted(value.func)
            if d and len(d) == 3 and d[0] == "self" and d[2] == "get":
                return vt.get(d[1])
            if d and len(d) == 2 and d[0] == "self":
                return mr.get(d[1])
            cc = _ctor_class(value)
            if cc is not None and cc.split(".")[-1] in ml.classes:
                return cc.split(".")[-1]
        return None

    def iter_class(it: ast.AST) -> Optional[str]:
        if isinstance(it, ast.Call):
            d = _dotted(it.func)
            if d is not None and len(d) == 1 \
                    and d[0] in ("list", "tuple", "sorted", "reversed") \
                    and it.args:
                return iter_class(it.args[0])
            if d is not None and len(d) == 3 and d[0] == "self" \
                    and d[2] == "values":
                return vt.get(d[1])
            return None
        attr = self_attr(it)
        return vt.get(attr) if attr else None

    for st in _own_scope_stmts(fndef):
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            v = src_class(st.value)
            if v:
                out[st.targets[0].id] = v
        elif isinstance(st, (ast.For, ast.AsyncFor)) \
                and isinstance(st.target, ast.Name):
            v = iter_class(st.iter)
            if v:
                out[st.target.id] = v
    return out


class _FnCtx:
    def __init__(self, fndef: ast.AST, qname: str, cls: Optional[str],
                 summary: Dict[str, Any], ml: _ModuleLocks,
                 known_locks: Set[str]):
        self.fndef = fndef
        self.qname = qname
        self.cls = cls
        self.summary = summary
        self.ml = ml
        self.known_locks = known_locks
        self.class_locks = ml.class_locks(cls)
        self.entry_tokens: Tuple[str, ...] = ()
        self.local_types = _local_record_types(fndef, cls, ml)
        # token -> (record class, lock attr) for locals typed above:
        # "rec.lock" resolves to that class's lock table entry, so roles,
        # reentrancy and the order graph see through local receivers
        self.typed_tokens: Dict[str, Tuple[str, str]] = {}

    def token_of_dotted(self, dotted: str) -> Optional[str]:
        if dotted.startswith("self.") and dotted.count(".") == 1:
            attr = dotted[5:]
            rec = self.class_locks.get(attr)
            if rec is not None:
                alias = rec.get("alias")
                return f"self.{alias}" if alias else dotted
        elif "." not in dotted and dotted in self.ml.globals_:
            return dotted
        elif dotted.count(".") == 1:
            base, attr = dotted.split(".")
            vcls = self.local_types.get(base)
            if vcls:
                rec = self.ml.classes.get(vcls, {}).get(attr)
                if rec is not None:
                    alias = rec.get("alias")
                    tok = f"{base}.{alias}" if alias else dotted
                    self.typed_tokens[tok] = (vcls, alias or attr)
                    return tok
        return None

    def token_of(self, expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if d is None:
            return None
        dotted = ".".join(d)
        tok = self.token_of_dotted(dotted)
        if tok is not None:
            return tok
        if _is_lockish(expr, self.known_locks):
            return dotted
        return None

    def lock_rec(self, token: str) -> Optional[Dict[str, Any]]:
        if token.startswith("self.") and token.count(".") == 1:
            return self.class_locks.get(token[5:])
        typed = self.typed_tokens.get(token)
        if typed is not None:
            return self.ml.classes.get(typed[0], {}).get(typed[1])
        return self.ml.globals_.get(token)

    def canonical(self, token: str) -> Optional[str]:
        """Project-wide key for a known lock token, else None."""
        mod = self.summary["module"]
        if token.startswith("self.") and token.count(".") == 1:
            if self.cls and token[5:] in self.class_locks:
                return f"{mod}.{self.cls}.{token[5:]}"
            return None
        typed = self.typed_tokens.get(token)
        if typed is not None:
            return f"{mod}.{typed[0]}.{typed[1]}"
        if token in self.ml.globals_:
            return f"{mod}.{token}"
        return None


def _timeout_bounded(call: ast.Call, skip_first: bool = False) -> bool:
    """True when a wait()/wait_for() call carries a real (non-None)
    timeout; `skip_first` skips wait_for's predicate argument."""
    args = call.args[1:] if skip_first else call.args
    for a in args:
        if not (isinstance(a, ast.Constant) and a.value is None):
            return True
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


def _nonblocking_acquire(call: ast.Call) -> bool:
    """``acquire(False)`` / ``acquire(blocking=False)`` / any timeout:
    the result, not the call, decides heldness."""
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return True
        if len(call.args) > 1:
            return True
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == "timeout":
            return True
    return False


class _Domain:
    """The held-lock MUST-domain over :class:`.dataflow.LockState`."""

    def __init__(self, ctx: _FnCtx):
        self.ctx = ctx

    def initial(self) -> LockState:
        return LockState.entry(self.ctx.entry_tokens)

    def join(self, a: LockState, b: LockState) -> LockState:
        return a.join(b)

    def assume(self, state: LockState, label) -> LockState:
        sense, name = label
        if sense in ("held", "unheld"):
            tok = self.ctx.token_of_dotted(name)
            if tok is None and "lock" in name.rsplit(".", 1)[-1].lower():
                tok = name
            if tok is None:
                return state
            return state.acquire_if_absent(tok) if sense == "held" \
                else state.release(tok)
        tok = state.bound_token(name)
        if tok is None:
            return state
        return state.acquire_if_absent(tok) if sense == "some" \
            else state.release(tok)

    def transfer(self, node, state: LockState) -> LockState:
        if node.kind == WITH_ENTER:
            tok = self.ctx.token_of(node.ast.context_expr)
            return state.acquire(tok) if tok else state
        if node.kind == WITH_EXIT:
            tok = self.ctx.token_of(node.ast.context_expr)
            return state.release(tok) if tok else state
        if node.kind == FOR_BIND:
            return state.unbind(_assigned_names(node.ast.target))
        if node.kind == STMT:
            return self._stmt(node.ast, state)
        return state

    def _stmt(self, stmt: ast.stmt, state: LockState) -> LockState:
        if isinstance(stmt, ast.Assert):
            t = stmt.test
            if isinstance(t, ast.Call) and isinstance(t.func, ast.Attribute) \
                    and t.func.attr == "locked":
                tok = self.ctx.token_of(t.func.value)
                if tok:
                    return state.acquire_if_absent(tok)
            return state
        for call in _iter_own_exprs(stmt):
            if not isinstance(call, ast.Call) \
                    or not isinstance(call.func, ast.Attribute):
                continue
            op = call.func.attr
            if op not in ("acquire", "release"):
                continue
            tok = self.ctx.token_of(call.func.value)
            if tok is None:
                continue
            if op == "release":
                state = state.release(tok)
            elif not _nonblocking_acquire(call):
                state = state.acquire(tok)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            names: List[str] = []
            for t in targets:
                names.extend(_assigned_names(t))
            state = state.unbind(names)
            if isinstance(stmt, ast.Assign) and len(names) == 1 \
                    and isinstance(stmt.value, ast.Call) \
                    and isinstance(stmt.value.func, ast.Attribute) \
                    and stmt.value.func.attr == "acquire" \
                    and _nonblocking_acquire(stmt.value):
                tok = self.ctx.token_of(stmt.value.func.value)
                if tok:
                    state = state.bind(names[0], tok)
        return state


# -- per-function event collection ------------------------------------------


class _FnEvents:
    """Everything one function contributes, keyed off final in-states."""

    def __init__(self) -> None:
        # (attr, write, line, col, frozenset(held tokens))
        self.attr_accesses: List[Tuple[str, bool, int, int, frozenset]] = []
        # token -> first acquire line (known locks only)
        self.acquires: Dict[str, int] = {}
        # (held_token, acquired_token, line) — known locks only
        self.edges: List[Tuple[str, str, int]] = []
        # (frozenset held tokens, callee dotted, line)
        self.calls_held: List[Tuple[frozenset, str, int]] = []
        # (method_name, frozenset held tokens, line) for self.m() calls
        self.intraclass_calls: List[Tuple[str, frozenset, int]] = []
        # (desc, exempt_token, line, frozenset held)
        self.blocking: List[Tuple[str, Optional[str], int, frozenset]] = []
        # (desc, line, frozenset held)
        self.cb_calls: List[Tuple[str, int, frozenset]] = []
        # (kind, key, node_idx, line, frozenset held)
        self.checks: List[Tuple[str, str, int, int, frozenset]] = []
        self.acts: List[Tuple[str, str, int, int, frozenset]] = []
        # (token, line) — non-reentrant lock acquired while already held
        self.reentries: List[Tuple[str, int]] = []
        self.states = 0


class _FnAnalysis:
    def __init__(self, ctx: _FnCtx):
        self.ctx = ctx
        self.events = _FnEvents()
        self.cfg = None
        self._if_tests: Set[int] = set()
        self._cb_names: Set[str] = set()
        self._get_lines: Set[int] = set()

    # -- prescan ----------------------------------------------------------

    def _prescan(self) -> None:
        ctx = self.ctx
        fnrec = ctx.summary["functions"].get(ctx.qname)
        if fnrec:
            self._get_lines = {g["lineno"] for g in fnrec.get("gets", ())}
        for stmt in _own_scope_stmts(ctx.fndef):
            if isinstance(stmt, ast.If):
                self._if_tests.add(id(stmt.test))
            if isinstance(stmt, ast.For):
                d = _dotted(stmt.iter)
                if d is not None and _CB_CONTAINER_RE.match(d[-1]):
                    self._cb_names.update(_assigned_names(stmt.target))
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                d = _dotted(stmt.value)
                if d is not None and (_CB_ATTR_RE.match(d[-1])
                                      or _CB_SUFFIX_RE.match(d[-1])):
                    self._cb_names.add(stmt.targets[0].id)

    # -- run --------------------------------------------------------------

    def run(self, stats: Dict[str, int]) -> bool:
        self._prescan()
        try:
            self.cfg = build_cfg(self.ctx.fndef)
        except CFGTooLarge:
            stats["fns_cfg_skipped"] = stats.get("fns_cfg_skipped", 0) + 1
            return False
        result = dataflow.run(self.cfg, _Domain(self.ctx))
        self.events = _FnEvents()
        self.events.states = len(result.in_states)
        for node in self.cfg.nodes:
            state = result.in_states.get(node.idx)
            if state is None:
                continue
            self._collect(node, state)
        return True

    # -- per-node event extraction ----------------------------------------

    def _held(self, state: LockState) -> frozenset:
        return state.tokens()

    def _known_held(self, state: LockState) -> List[str]:
        return [t for t in sorted(state.tokens())
                if self.ctx.lock_rec(t) is not None]

    def _note_acquire(self, tok: str, line: int, state: LockState) -> None:
        ev = self.events
        rec = self.ctx.lock_rec(tok)
        if rec is not None:
            ev.acquires.setdefault(tok, line)
            if state.has(tok) and not rec["reentrant"]:
                ev.reentries.append((tok, line))
        for held in self._known_held(state):
            if held != tok and rec is not None:
                ev.edges.append((held, tok, line))

    def _collect(self, node, state: LockState) -> None:
        ev = self.events
        ctx = self.ctx
        if node.kind == WITH_ENTER:
            tok = ctx.token_of(node.ast.context_expr)
            if tok:
                self._note_acquire(tok, node.lineno, state)
            return
        if node.kind == STMT:
            exprs = list(_iter_own_exprs(node.ast))
            write_ids = _write_attr_ids(node.ast)
        elif node.kind == TEST:
            exprs = list(_walk_expr(node.ast))
            write_ids = set()
        else:
            return
        held = self._held(state)
        for sub in exprs:
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "self":
                write = id(sub) in write_ids \
                    or not isinstance(sub.ctx, ast.Load)
                ev.attr_accesses.append(
                    (sub.attr, write, sub.lineno, sub.col_offset + 1, held))
            if isinstance(sub, ast.Call):
                self._call(sub, node, state, held)
        if node.kind == TEST and id(node.ast) in self._if_tests:
            self._check_site(node, state, held)
        if node.kind == STMT:
            self._act_sites(node, state, held)

    def _call(self, call: ast.Call, node, state: LockState,
              held: frozenset) -> None:
        ev = self.events
        ctx = self.ctx
        func = call.func
        d = _dotted(func)
        # acquire sites (edges + reentry); heldness handled by the domain
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            tok = ctx.token_of(func.value)
            if tok:
                self._note_acquire(tok, call.lineno, state)
            return
        if isinstance(func, ast.Attribute) \
                and func.attr in ("release", "locked", "__enter__",
                                  "__exit__"):
            return
        # blocking calls
        bk = self._blocking_kind(call)
        if bk is not None:
            desc, exempt = bk
            eff = held - {exempt} if exempt else held
            if eff:
                ev.blocking.append((desc, exempt, call.lineno, eff))
        # stored-callback invocations
        cb = self._callback_desc(call)
        if cb is not None and held:
            ev.cb_calls.append((cb, call.lineno, held))
        # interprocedural facts
        if d is None:
            return
        name = ".".join(d)
        if isinstance(func, ast.Attribute) and func.attr in (
                "set", "clear", "is_set", "wait", "wait_for", "notify",
                "notify_all", "append", "get", "put", "join", "recv",
                "recv_bytes", "send"):
            return
        if held:
            known = frozenset(self._known_held(state))
            if known:
                ev.calls_held.append((known, name, call.lineno))
        if len(d) == 2 and d[0] == "self" and ctx.cls:
            ev.intraclass_calls.append((d[1], held, call.lineno))

    # -- blocking classification ------------------------------------------

    def _blocking_kind(self, call: ast.Call
                       ) -> Optional[Tuple[str, Optional[str]]]:
        func = call.func
        ctx = self.ctx
        if call.lineno in self._get_lines:
            return ("blocking get()", None)
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value
        d = _dotted(recv)
        if attr in ("recv", "recv_bytes"):
            return (f"{attr}()", None)
        if attr == "join" and d is not None:
            kind = self._sync_kind(d)
            if kind == "thread" or (kind is None
                                    and _THREADISH_RE.match(d[-1].lower())):
                return ("join()", None)
            return None
        if attr in ("wait", "wait_for") and d is not None:
            if _timeout_bounded(call, skip_first=(attr == "wait_for")):
                return None
            tok = ctx.token_of(recv)
            if tok is not None:
                rec = ctx.lock_rec(tok)
                if rec is not None and rec["kind"] == "condition":
                    # cond.wait releases its own lock while waiting
                    return (f"{attr}() on condition", tok)
            if self._sync_kind(d) == "event":
                return ("wait() with no timeout", None)
            return None
        if attr == "get" and d is not None \
                and self._sync_kind(d) == "queue":
            if call.args or any(kw.arg == "timeout" for kw in call.keywords):
                return None
            return ("Queue.get() with no timeout", None)
        return None

    def _sync_kind(self, d: Tuple[str, ...]) -> Optional[str]:
        ml = self.ctx.ml
        if len(d) == 2 and d[0] == "self" and self.ctx.cls:
            return ml.sync.get(self.ctx.cls, {}).get(d[1])
        if len(d) == 1:
            return ml.global_sync.get(d[0])
        return None

    # -- callback classification ------------------------------------------

    def _callback_desc(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name) and func.id in self._cb_names:
            return f"'{func.id}'"
        if isinstance(func, ast.Subscript):
            d = _dotted(func.value)
            if d is not None and _CB_CONTAINER_RE.match(d[-1]):
                return f"'{'.'.join(d)}[...]'"
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name) \
                and func.value.id == "self" and self.ctx.cls:
            attr = func.attr
            if not (_CB_ATTR_RE.match(attr) or _CB_SUFFIX_RE.match(attr)):
                return None
            cls_rec = self.ctx.summary["classes"].get(self.ctx.cls, {})
            if attr in cls_rec.get("methods", ()):
                return None   # a real method: the transitive rule owns it
            if attr in self.ctx.ml.attr_types.get(self.ctx.cls, {}):
                return None   # typed composition object, resolvable
            return f"'self.{attr}'"
        return None

    # -- GC054 sites -------------------------------------------------------

    def _check_site(self, node, state: LockState, held: frozenset) -> None:
        for atom in _test_atoms(node.ast):
            kind_key = self._sync_atom(atom)
            if kind_key is not None:
                self.events.checks.append(
                    (*kind_key, node.idx, node.lineno, held))

    def _sync_atom(self, atom: ast.AST) -> Optional[Tuple[str, str]]:
        ctx = self.ctx
        if isinstance(atom, ast.Call) and isinstance(atom.func,
                                                     ast.Attribute) \
                and atom.func.attr == "is_set":
            d = _dotted(atom.func.value)
            if d is not None and self._sync_kind(d) == "event":
                return ("event", ".".join(d))
        if isinstance(atom, ast.Compare) and len(atom.ops) == 1:
            op = atom.ops[0]
            if isinstance(op, (ast.In, ast.NotIn)):
                d = _dotted(atom.comparators[0])
                if d is not None and len(d) == 2 and d[0] == "self" \
                        and ctx.cls:
                    return ("member", ".".join(d))
            if isinstance(op, (ast.Is, ast.IsNot)) \
                    and isinstance(atom.comparators[0], ast.Constant) \
                    and atom.comparators[0].value is None:
                d = _dotted(atom.left)
                if d is not None and len(d) == 2 and d[0] == "self":
                    return ("none", ".".join(d))
        return None

    def _act_sites(self, node, state: LockState, held: frozenset) -> None:
        ev = self.events
        stmt = node.ast
        ctx = self.ctx
        for call in _iter_own_exprs(stmt):
            if isinstance(call, ast.Call) \
                    and isinstance(call.func, ast.Attribute):
                d = _dotted(call.func.value)
                if d is None:
                    continue
                if call.func.attr in ("set", "clear") \
                        and self._sync_kind(d) == "event":
                    ev.acts.append(("event", ".".join(d), node.idx,
                                    call.lineno, held))
                if call.func.attr == "pop" and len(d) == 2 \
                        and d[0] == "self":
                    ev.acts.append(("member", ".".join(d), node.idx,
                                    call.lineno, held))
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for t in targets:
            if isinstance(t, ast.Subscript):
                d = _dotted(t.value)
                if d is not None and len(d) == 2 and d[0] == "self":
                    ev.acts.append(("member", ".".join(d), node.idx,
                                    t.value.lineno, held))
            if isinstance(t, ast.Attribute) and isinstance(t.value,
                                                           ast.Name) \
                    and t.value.id == "self":
                ev.acts.append(("none", f"self.{t.attr}", node.idx,
                                t.lineno, held))

    # -- reachability (GC054 pairing) --------------------------------------

    def reachable_from(self, idx: int) -> Set[int]:
        seen = {idx}
        stack = [idx]
        while stack:
            cur = stack.pop()
            for dst, _, _ in self.cfg.succ[cur]:
                if dst not in seen:
                    seen.add(dst)
                    stack.append(dst)
        return seen


def _has_lock_syntax(fndef: ast.AST) -> bool:
    """Cheap triviality gate: can this function possibly hold a lock on
    its own (with-statement or manual acquire/release)? Functions that
    cannot, in classes and modules with no locks or sync objects, skip
    the CFG + fixpoint entirely."""
    for node in ast.walk(fndef):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            return True
        if isinstance(node, ast.Attribute) \
                and node.attr in ("acquire", "release", "locked"):
            return True
    return False


def _test_atoms(expr: ast.AST) -> List[ast.AST]:
    """The comparable atoms of an if-test: the expr, its ``not``
    operand, and each BoolOp conjunct (one level)."""
    out: List[ast.AST] = []
    worklist = [expr]
    while worklist:
        e = worklist.pop()
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
            worklist.append(e.operand)
        elif isinstance(e, ast.BoolOp):
            worklist.extend(e.values)
        else:
            out.append(e)
    return out


def _write_attr_ids(stmt: ast.stmt) -> Set[int]:
    """ids of self-attr Attribute nodes written by this statement:
    assignment/deletion targets, subscript-store receivers, and
    receivers of known mutating container methods."""
    out: Set[int] = set()

    def note_target(t: ast.AST) -> None:
        if isinstance(t, ast.Attribute):
            out.add(id(t))
        if isinstance(t, ast.Subscript) and isinstance(t.value,
                                                       ast.Attribute):
            out.add(id(t.value))
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                note_target(e)
        if isinstance(t, ast.Starred):
            note_target(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            note_target(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        note_target(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            note_target(t)
    for sub in _iter_own_exprs(stmt):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _MUTATORS \
                and isinstance(sub.func.value, ast.Attribute):
            out.add(id(sub.func.value))
    return out


# -- module pass ------------------------------------------------------------


def analyze_module(tree: ast.Module, summary: Dict[str, Any]
                   ) -> List[Finding]:
    """GC050/GC053/GC054 + the module-local GC051 forms over every
    function; exports lock tables and held-call facts into `summary`
    for the project pass (GC051 transitive, GC052 order graph)."""
    findings: List[Finding] = []
    stats: Dict[str, int] = {}
    ml = _discover(tree)
    known_locks = set(summary.get("module_unser", ()))
    analyses: Dict[str, _FnAnalysis] = {}
    by_class: Dict[str, List[str]] = {}

    module_has_locks = bool(ml.globals_ or ml.global_sync)
    for fndef, qname, cls in collect_functions(tree):
        stats["fns_total"] = stats.get("fns_total", 0) + 1
        if is_generator(fndef):
            stats["fns_generators_skipped"] = \
                stats.get("fns_generators_skipped", 0) + 1
            continue
        cls_relevant = cls is not None and (cls in ml.classes
                                            or cls in ml.sync)
        if not cls_relevant and not module_has_locks \
                and not _has_lock_syntax(fndef):
            stats["fns_trivial"] = stats.get("fns_trivial", 0) + 1
            continue
        ctx = _FnCtx(fndef, qname, cls, summary, ml, known_locks)
        fa = _FnAnalysis(ctx)
        try:
            ok = fa.run(stats)
        except Exception:        # never fail the lint on one function
            stats["fns_errors"] = stats.get("fns_errors", 0) + 1
            continue
        if not ok:
            continue
        stats["fns_analyzed"] = stats.get("fns_analyzed", 0) + 1
        stats["held_states"] = stats.get("held_states", 0) \
            + fa.events.states
        analyses[qname] = fa
        if cls:
            by_class.setdefault(cls, []).append(qname)

    _helper_pass(analyses, by_class, ml, stats)

    stats["locks_discovered"] = sum(len(v) for v in ml.classes.values()) \
        + len(ml.globals_)
    stats["classes_with_locks"] = len(ml.classes)

    for qname, fa in analyses.items():
        findings.extend(_function_findings(fa))
    findings.extend(_guarded_by(summary, ml, analyses, by_class, stats))
    _export(summary, ml, analyses, stats)
    return findings


def _helper_pass(analyses: Dict[str, _FnAnalysis],
                 by_class: Dict[str, List[str]], ml: _ModuleLocks,
                 stats: Dict[str, int]) -> None:
    """Intraclass helper entry inference, iterated to a fixpoint: a
    private method whose every intraclass call site holds L is
    re-analyzed as entered-with-L. Iteration lets heldness cascade
    through helper chains (create -> _ensure_space -> _evict ->
    _release_entry); entries only grow, so this converges."""
    for cls, qnames in by_class.items():
        if cls not in ml.classes:
            continue
        for _round in range(5):
            changed = False
            sites: Dict[str, List[frozenset]] = {}
            for q in qnames:
                if q.count(".") >= 2:
                    continue   # a closure's entry state is unknown: its
                    # call sites are not evidence against heldness
                for m, held, _line in analyses[q].events.intraclass_calls:
                    sites.setdefault(m, []).append(held)
            for m, helds in sites.items():
                q = f"{cls}.{m}"
                fa = analyses.get(q)
                if fa is None or not m.startswith("_") \
                        or m.startswith("__"):
                    continue
                entry = frozenset.intersection(*helds) if helds \
                    else frozenset()
                entry = tuple(sorted(
                    t for t in entry if fa.ctx.lock_rec(t) is not None))
                if not entry or entry == fa.ctx.entry_tokens:
                    continue
                fa.ctx.entry_tokens = entry
                if fa.run(stats):
                    changed = True
                    stats["helper_reruns"] = \
                        stats.get("helper_reruns", 0) + 1
            if not changed:
                break


def _function_findings(fa: _FnAnalysis) -> List[Finding]:
    out: List[Finding] = []
    ctx = fa.ctx
    ev = fa.events
    path = ctx.summary["path"]

    def role(tok: str) -> str:
        rec = ctx.lock_rec(tok)
        if rec and rec.get("role"):
            return rec["role"]
        return tok

    def report(rule: str, line: int, message: str) -> None:
        if not suppressed(ctx.summary, line, rule):
            out.append(Finding(path=path, line=line, col=1, rule=rule,
                               message=message))

    for tok, line in ev.reentries:
        report("GC051", line,
               f"re-acquiring non-reentrant lock '{role(tok)}' already "
               f"held on this path in {ctx.qname}: guaranteed "
               f"self-deadlock (use reentrant=True or drop the lock "
               f"first)")
    seen_cb = set()
    for desc, line, held in ev.cb_calls:
        key = (desc, line)
        if key in seen_cb:
            continue
        seen_cb.add(key)
        roles = ", ".join(sorted(role(t) for t in held))
        report("GC051", line,
               f"stored callback {desc} invoked while holding "
               f"[{roles}] in {ctx.qname}: a callback that re-enters "
               f"this class deadlocks (the peer-connect class) — invoke "
               f"it after releasing the lock")
    seen_blk = set()
    for desc, _exempt, line, held in ev.blocking:
        if line in seen_blk:
            continue
        seen_blk.add(line)
        roles = ", ".join(sorted(role(t) for t in held))
        report("GC053", line,
               f"{desc} reached while holding [{roles}] in "
               f"{ctx.qname}: one slow peer wedges every thread queued "
               f"on the lock — release before blocking")
    # GC054: check-then-act pairing over CFG reachability
    reach_memo: Dict[int, Set[int]] = {}
    seen_cta = set()
    for ckind, ckey, cidx, cline, cheld in ev.checks:
        for akind, akey, aidx, aline, aheld in ev.acts:
            if akind != ckind or akey != ckey or aidx == cidx:
                continue
            if ckind != "event" and not cheld:
                continue   # unlocked check: nothing was dropped in between
            if cheld & aheld:
                continue   # a common lock spans both: atomic
            if cidx not in reach_memo:
                reach_memo[cidx] = fa.reachable_from(cidx)
            if aidx not in reach_memo[cidx]:
                continue
            key = (ckey, cline, aline)
            if key in seen_cta:
                continue
            seen_cta.add(key)
            what = {"event": "Event tested with is_set()",
                    "member": "membership tested",
                    "none": "None-tested"}[ckind]
            why = "but the guard lock was released in between" if cheld \
                else "with no lock spanning test and mutation"
            report("GC054", aline,
                   f"non-atomic check-then-act on {ckey} in "
                   f"{ctx.qname}: {what} at line {cline}, mutated here "
                   f"{why} — two racing threads both pass the test")
    return out


def _init_only_methods(analyses: Dict[str, _FnAnalysis],
                       qnames: List[str]) -> Set[str]:
    """Private methods whose every intraclass call site sits in a
    dunder (or another such method): the init path runs before any
    worker thread exists, so the constructor escape extends to them."""
    callers: Dict[str, Set[str]] = {}
    for q in qnames:
        caller = q.rsplit(".", 1)[-1]
        for m, _held, _line in analyses[q].events.intraclass_calls:
            callers.setdefault(m, set()).add(caller)

    def is_dunder(m: str) -> bool:
        return m.startswith("__") and m.endswith("__")

    out: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for m, cs in callers.items():
            if m in out or not m.startswith("_") or is_dunder(m):
                continue
            if cs and all(is_dunder(c) or c in out for c in cs):
                out.add(m)
                changed = True
    return out


def _guarded_by(summary: Dict[str, Any], ml: _ModuleLocks,
                analyses: Dict[str, _FnAnalysis],
                by_class: Dict[str, List[str]], stats: Dict[str, int]
                ) -> List[Finding]:
    """GC050: per class, infer each attribute's guard lock from the
    majority of accesses, then flag accesses holding no lock at all."""
    out: List[Finding] = []
    for cls, qnames in by_class.items():
        locks = ml.classes.get(cls)
        if not locks:
            continue
        sync = ml.sync.get(cls, {})
        types = ml.attr_types.get(cls, {})
        init_only = _init_only_methods(analyses, qnames)
        # attr -> [(guard-or-None, write, line, col, qname)]
        acc: Dict[str, List[Tuple[Optional[frozenset], bool, int, int,
                                  str]]] = {}
        for q in qnames:
            method = q.split(".", 1)[-1].split(".")[0] if "." in q else q
            if method.startswith("__") and method.endswith("__"):
                continue    # constructor escape + debug surfaces
            if method in init_only:
                continue    # init path: runs before any thread exists
            # a nested closure's entry state is unknown (it may run
            # under the enclosing with-block's lock, or escape): its
            # accesses are neither guard evidence nor bare accesses
            closure = q.count(".") >= 2
            fa = analyses[q]
            for attr, write, line, col, held in fa.events.attr_accesses:
                if attr in locks or attr in sync or attr in types \
                        or attr.startswith("__"):
                    continue
                known = frozenset(
                    t for t in held if fa.ctx.lock_rec(t) is not None)
                guards = known if known else (
                    frozenset() if not held and not closure else None)
                # `guards is None` => only unknown (fallback) locks held:
                # neither evidence for a guard nor a bare access
                acc.setdefault(attr, []).append(
                    (guards, write, line, col, q))
        for attr, accesses in sorted(acc.items()):
            if not any(w for _, w, _, _, _ in accesses):
                continue    # init-only / effectively immutable
            counted = [a for a in accesses if a[0] is not None]
            if len(counted) < 3:
                continue
            tally: Dict[str, int] = {}
            for guards, _, _, _, _ in counted:
                for g in guards:
                    tally[g] = tally.get(g, 0) + 1
            if not tally:
                continue
            guard = max(sorted(tally), key=lambda g: tally[g])
            n = tally[guard]
            if n < 2 or n * 4 < len(counted) * 3:
                continue    # no majority (>= 75%) guard
            stats["guards_inferred"] = stats.get("guards_inferred", 0) + 1
            rec = ml.classes[cls].get(guard[5:]) if guard.startswith(
                "self.") else ml.globals_.get(guard)
            gname = rec["role"] if rec and rec.get("role") else guard
            for guards, write, line, col, q in counted:
                if guards:
                    continue   # some known lock held: not the bare class
                if suppressed(summary, line, "GC050"):
                    continue
                verb = "written" if write else "read"
                out.append(Finding(
                    path=summary["path"], line=line, col=col,
                    rule="GC050",
                    message=(f"self.{attr} is guarded by '{gname}' on "
                             f"{n}/{len(counted)} accesses but {verb} "
                             f"here ({q}) with no lock held — "
                             f"stale-read/lost-update hazard")))
    return out


def _export(summary: Dict[str, Any], ml: _ModuleLocks,
            analyses: Dict[str, _FnAnalysis], stats: Dict[str, int]
            ) -> None:
    mod = summary["module"]
    locks: Dict[str, Dict[str, Any]] = {}
    for cls, attrs in ml.classes.items():
        for attr, rec in attrs.items():
            locks[f"{mod}.{cls}.{attr}"] = {
                "role": rec.get("role"), "reentrant": rec["reentrant"],
                "kind": rec["kind"], "line": rec["line"],
                "alias": rec.get("alias"), "scope": "attr"}
    for name, rec in ml.globals_.items():
        locks[f"{mod}.{name}"] = {
            "role": rec.get("role"), "reentrant": rec["reentrant"],
            "kind": rec["kind"], "line": rec["line"], "alias": None,
            "scope": "global"}
    conc: Dict[str, Any] = {"stats": stats}
    if locks:
        conc["locks"] = locks
    if ml.attr_types:
        conc["attr_types"] = {c: dict(t) for c, t in ml.attr_types.items()}
    summary["concurrency"] = conc
    for qname, fa in analyses.items():
        ev = fa.events
        acquires = {}
        for tok, line in ev.acquires.items():
            key = fa.ctx.canonical(tok)
            if key:
                acquires[key] = line
        edges = []
        for a, b, line in ev.edges:
            ka, kb = fa.ctx.canonical(a), fa.ctx.canonical(b)
            if ka and kb and ka != kb:
                edges.append([ka, kb, line])
        calls_held = []
        for held, callee, line in ev.calls_held:
            keys = sorted(k for k in (fa.ctx.canonical(t) for t in held)
                          if k)
            if keys:
                calls_held.append([keys, callee, line])
        if acquires or edges or calls_held:
            fnrec = summary["functions"].get(qname)
            if fnrec is not None:
                fnrec["concurrency"] = {
                    "acquires": acquires, "edges": edges,
                    "calls_held": calls_held}


# -- project pass -----------------------------------------------------------


class _ProjectLocks:
    """Cross-module lock table + transitive-acquire closures."""

    _MAX_NODES = 4096

    def __init__(self, index):
        self.index = index
        self.locks: Dict[str, Dict[str, Any]] = {}
        for s in index.summaries:
            self.locks.update((s.get("concurrency") or {}).get("locks", {}))
        self._callees: Dict[str, List[Tuple[str, int, str]]] = {}
        self._tacq: Dict[str, Dict[str, Tuple[Optional[str], int]]] = {}
        self._tacq_self: Dict[str, Set[str]] = {}

    def role(self, key: str) -> str:
        rec = self.locks.get(key, {})
        return rec.get("role") or key

    def reentrant(self, key: str) -> bool:
        return bool(self.locks.get(key, {}).get("reentrant"))

    def resolve_callee(self, summary, fn, name: str) -> Optional[str]:
        from .engine import resolve_call_target

        parts = name.split(".")
        if len(parts) == 3 and parts[0] == "self" and fn.get("cls"):
            types = (summary.get("concurrency") or {}).get(
                "attr_types", {}).get(fn["cls"], {})
            ctor = types.get(parts[1])
            if ctor:
                cls_fq = self.index.resolve_class(summary, ctor)
                if cls_fq:
                    cand = f"{cls_fq}.{parts[2]}"
                    if cand in self.index.functions:
                        return cand
            return None
        return resolve_call_target(self.index, summary, fn, name)

    def callees(self, fq: str) -> List[Tuple[str, int, str]]:
        got = self._callees.get(fq)
        if got is not None:
            return got
        summary, fn = self.index.functions[fq]
        out: List[Tuple[str, int, str]] = []
        for call in fn.get("calls", ()):
            tgt = self.resolve_callee(summary, fn, call["name"])
            if tgt is not None and tgt != fq:
                out.append((tgt, call["lineno"], call["name"]))
        self._callees[fq] = out
        return out

    def tacq(self, fq: str) -> Dict[str, Tuple[Optional[str], int]]:
        """Transitive acquires of `fq` following every resolvable call:
        lock key -> (via callee fq or None-if-direct, site line)."""
        if fq in self._tacq:
            return self._tacq[fq]
        # collect the reachable subgraph, then iterate to a fixpoint
        order: List[str] = []
        seen = {fq}
        stack = [fq]
        while stack and len(seen) < self._MAX_NODES:
            cur = stack.pop()
            order.append(cur)
            for tgt, _, _ in self.callees(cur):
                if tgt not in seen:
                    seen.add(tgt)
                    stack.append(tgt)
        acq: Dict[str, Dict[str, Tuple[Optional[str], int]]] = {}
        for f in order:
            _, fn = self.index.functions[f]
            own = (fn.get("concurrency") or {}).get("acquires", {})
            acq[f] = {k: (None, line) for k, line in own.items()}
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for f in reversed(order):
                mine = acq[f]
                for tgt, line, _name in self.callees(f):
                    for k in acq.get(tgt, ()):
                        if k not in mine:
                            mine[k] = (tgt, line)
                            changed = True
        for f, m in acq.items():
            self._tacq.setdefault(f, m)
        return self._tacq[fq]

    def tacq_self(self, fq: str) -> Set[str]:
        """Transitive acquires following only same-class ``self.m()``
        calls — the same-instance discipline GC051 needs for class-attr
        locks (another instance's ``self._lock`` is a different object)."""
        if fq in self._tacq_self:
            return self._tacq_self[fq]
        cls_prefix = fq.rsplit(".", 1)[0]
        seen = {fq}
        stack = [fq]
        out: Set[str] = set()
        while stack:
            cur = stack.pop()
            summary, fn = self.index.functions[cur]
            out.update((fn.get("concurrency") or {}).get("acquires", {}))
            for call in fn.get("calls", ()):
                parts = call["name"].split(".")
                if len(parts) == 2 and parts[0] == "self":
                    tgt = f"{cls_prefix}.{parts[1]}"
                    if tgt in self.index.functions and tgt not in seen:
                        seen.add(tgt)
                        stack.append(tgt)
        self._tacq_self[fq] = out
        return out

    def chain(self, fq: str, key: str, depth: int = 8) -> str:
        """Human-readable acquire chain for a transitive key."""
        hops = []
        cur = fq
        while depth > 0:
            depth -= 1
            via = self.tacq(cur).get(key)
            if via is None:
                break
            nxt, line = via
            if nxt is None:
                hops.append(f"acquires '{self.role(key)}' at line {line}")
                break
            hops.append(f"{nxt.rsplit('.', 1)[-1]} (line {line})")
            cur = nxt
        return " -> ".join(hops) if hops else f"acquires '{self.role(key)}'"


def build_lock_order_graph(index) -> Dict[Tuple[str, str],
                                          Tuple[str, int, str]]:
    """The static role-level lock-order graph, project-wide.

    Edges come from (a) directly nested held states and (b) every call
    made with locks held, crossed with the callee's transitive
    acquires. Returns ``(role_held, role_acquired) -> (path, line,
    via)`` with the lexically-first witness site per edge. The dynamic
    order graph observed under ``RAY_TPU_DEBUG_LOCKS=1`` must be a
    subgraph of this (``scripts/locks_gate.py``).
    """
    pl = _ProjectLocks(index)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def note(ra: str, rb: str, site: Tuple[str, int, str]) -> None:
        if ra == rb:
            return
        prev = edges.get((ra, rb))
        if prev is None or (site[0], site[1]) < (prev[0], prev[1]):
            edges[(ra, rb)] = site

    for s in index.summaries:
        for fn in s["functions"].values():
            conc = fn.get("concurrency")
            if not conc:
                continue
            for a, b, line in conc.get("edges", ()):
                note(pl.role(a), pl.role(b), (s["path"], line, ""))
            for held, callee, line in conc.get("calls_held", ()):
                fq = pl.resolve_callee(s, fn, callee)
                if fq is None:
                    continue
                for k in pl.tacq(fq):
                    for h in held:
                        note(pl.role(h), pl.role(k),
                             (s["path"], line, f"via {callee}"))
    return edges


def project_lock_roles(index) -> List[str]:
    """Every known lock role, project-wide: the instrumented role string
    ('*'-wildcarded for f-string shard roles) or the canonical dotted
    token for plain locks. ``scripts/locks_gate.py`` uses this to
    recognize dynamic edges between two shards of one wildcard family,
    which the static graph collapses to a single (self-)role and
    therefore never lists as an edge."""
    pl = _ProjectLocks(index)
    return sorted({pl.role(k) for k in pl.locks})


def _sccs(edges: Dict[Tuple[str, str], Any]) -> List[List[str]]:
    """Tarjan SCCs (iterative) of the role graph; only components with
    at least one internal cycle are returned."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in idx:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                idx[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on.add(v)
            advanced = False
            nbrs = adj[v]
            while pi < len(nbrs):
                w = nbrs[pi]
                pi += 1
                work[-1] = (v, pi)
                if w not in idx:
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == idx[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out


def run(index, enabled: Set[str]) -> List[Finding]:
    """Project pass: GC052 order-graph cycles and GC051 transitive
    re-acquires through the resolvable call graph."""
    out: List[Finding] = []
    if not ({"GC051", "GC052"} & enabled):
        return out
    pl = _ProjectLocks(index)

    if "GC052" in enabled:
        edges = build_lock_order_graph(index)
        for comp in _sccs(edges):
            members = set(comp)
            hops = sorted((a, b) for a, b in edges
                          if a in members and b in members)
            sites = []
            for a, b in hops:
                path, line, via = edges[(a, b)]
                note = f" {via}" if via else ""
                sites.append(f"{a} -> {b} ({path}:{line}{note})")
            path, line, _ = edges[hops[0]]
            s = next((s for s in index.summaries if s["path"] == path),
                     None)
            if s is not None and suppressed(s, line, "GC052"):
                continue
            out.append(Finding(
                path=path, line=line, col=1, rule="GC052",
                message=("lock-order cycle between roles "
                         f"[{', '.join(comp)}]: " + "; ".join(sites)
                         + " — the AB/BA deadlock precondition; pick "
                         "one global order")))

    if "GC051" in enabled:
        for s in index.summaries:
            for fn in s["functions"].values():
                conc = fn.get("concurrency")
                if not conc:
                    continue
                for held, callee, line in conc.get("calls_held", ()):
                    if suppressed(s, line, "GC051"):
                        continue
                    fq = pl.resolve_callee(s, fn, callee)
                    if fq is None:
                        continue
                    for k in held:
                        if pl.reentrant(k):
                            continue
                        if pl.locks.get(k, {}).get("scope") == "attr":
                            # same-instance chains only: self.m() calls
                            if not (callee.startswith("self.")
                                    and callee.count(".") == 1):
                                continue
                            hit = k in pl.tacq_self(fq)
                        else:
                            hit = k in pl.tacq(fq)
                        if not hit:
                            continue
                        out.append(Finding(
                            path=s["path"], line=line, col=1,
                            rule="GC051",
                            message=(f"call to {callee} while holding "
                                     f"'{pl.role(k)}': the callee "
                                     f"transitively re-acquires it "
                                     f"({pl.chain(fq, k)}) — "
                                     f"non-reentrant self-deadlock")))
    return out


def aggregate_stats(summaries) -> Dict[str, int]:
    total: Dict[str, int] = {}
    for s in summaries:
        for k, v in (s.get("concurrency") or {}).get("stats", {}).items():
            total[k] = total.get(k, 0) + int(v)
    return total
