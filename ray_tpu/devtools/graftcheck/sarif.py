"""SARIF 2.1.0 output — GitHub code-scanning ingests this directly, so
CI findings surface as inline PR annotations (`--sarif`, wired in
.github/workflows/ci.yml)."""
from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence

from .local import RULES, Finding

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
SARIF_VERSION = "2.1.0"
TOOL_VERSION = "5.0.0"


def _uri(path: str, base: str) -> str:
    try:
        rel = os.path.relpath(os.path.abspath(path), base)
    except ValueError:
        rel = path
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


def to_sarif(findings: Sequence[Finding],
             base_dir: str = ".") -> Dict[str, Any]:
    """Findings -> a SARIF 2.1.0 log (one run, one result per finding,
    URIs relative to `base_dir` so code-scanning can anchor them)."""
    base = os.path.abspath(base_dir)
    rules: List[Dict[str, Any]] = [
        {"id": rid,
         "shortDescription": {"text": desc},
         "helpUri": "docs/GRAFTCHECK.md",
         "defaultConfiguration": {"level": "warning"}}
        for rid, desc in sorted(RULES.items())]
    results: List[Dict[str, Any]] = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": sorted(RULES).index(f.rule),
            "level": "warning",
            "message": {"text": f"{f.rule}: {f.message}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(f.path, base),
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": max(f.col, 1)},
                }}],
            "partialFingerprints": {
                "graftcheck/v1": f"{f.rule}:{_uri(f.path, base)}:{f.line}",
            }})
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftcheck",
                "informationUri":
                    "https://github.com/ray-tpu/ray_tpu"
                    "/blob/main/docs/GRAFTCHECK.md",
                "version": TOOL_VERSION,
                "rules": rules,
            }},
            "originalUriBaseIds": {
                "SRCROOT": {"uri": "file://" + base.replace(os.sep, "/")
                            + "/"}},
            "results": results,
        }],
    }
