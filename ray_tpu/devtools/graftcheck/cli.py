"""graftcheck CLI: the ``check`` surface (default) plus the ``graph``
subcommand.

::

    python -m ray_tpu.devtools.graftcheck [--json] [--sarif F] \
        [--baseline F] [--write-baseline F] [--rules ...] \
        [--cache F | --no-cache] [--no-project] [--diff REF] \
        [--stats] paths...
    python -m ray_tpu.devtools.graftcheck graph [--out F] paths...
    python -m ray_tpu.devtools.graftcheck locks [--dot | --json] \
        [--out F] paths...

``--diff REF`` scopes reporting to files changed vs the git ref plus
their reverse-dependency closure from the project index (everything
whose cross-file facts could see the change). The full index is still
built — cross-file resolution needs it — but unchanged files come from
the content-hash cache, so a one-file change lints in well under a
second warm.

Exit status: 0 = clean, 1 = findings, 2 = usage/parse errors only.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from . import baseline as baseline_mod
from . import engine as engine_mod
from . import sarif as sarif_mod
from .local import RULES, Finding, check_file, iter_python_files


def _parse_rules(spec: str) -> Optional[set]:
    rules = {r.strip().upper() for r in spec.split(",") if r.strip()}
    unknown = rules - set(RULES)
    if unknown:
        print(f"unknown rule(s): {', '.join(sorted(unknown))}",
              file=sys.stderr)
        return None
    return rules


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(argv) if argv is not None else sys.argv[1:]
    if argv and argv[0] == "graph":
        return _graph_main(argv[1:])
    if argv and argv[0] == "locks":
        return _locks_main(argv[1:])
    return _check_main(argv)


# ---------------------------------------------------------------------------
# check (default)


def _check_main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.graftcheck",
        description="framework-aware static analysis for ray_tpu code "
                    "(whole-program engine; see docs/GRAFTCHECK.md)")
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as a JSON array")
    parser.add_argument("--sarif", metavar="FILE",
                        help="write SARIF 2.1.0 to FILE ('-' = stdout) "
                             "for GitHub code-scanning annotations")
    parser.add_argument("--baseline", metavar="FILE",
                        help="suppress findings fingerprinted in FILE")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="record current findings as the baseline "
                             "and exit 0")
    parser.add_argument("--rules", default="",
                        help="comma-separated subset (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--cache", metavar="FILE",
                        default=engine_mod.default_cache_path(),
                        help="content-hash file cache (default: "
                             "$GRAFTCHECK_CACHE or ~/.cache/graftcheck/"
                             "cache.json)")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the file cache")
    parser.add_argument("--no-project", action="store_true",
                        help="per-file rules only: skip the whole-program "
                             "engine (GC010/GC011/GC020-series; GC008 "
                             "falls back to module-local matching)")
    parser.add_argument("--diff", metavar="REF",
                        help="report only findings in files changed vs "
                             "git REF plus their reverse-dependency "
                             "closure (needs the project engine)")
    parser.add_argument("--stats", action="store_true",
                        help="print engine timing + cache hit counts to "
                             "stderr")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0
    if not args.paths:
        parser.error("the following arguments are required: paths")

    rules = set(RULES)
    if args.rules:
        parsed = _parse_rules(args.rules)
        if parsed is None:
            return 2
        rules = parsed

    if args.diff and args.no_project:
        parser.error("--diff needs the project engine "
                     "(drop --no-project)")

    lifecycle_stats: dict = {}
    shape_stats: dict = {}
    concurrency_stats: dict = {}
    diff_note = ""
    t0 = time.monotonic()
    if args.no_project:
        try:
            files = iter_python_files(args.paths)
        except FileNotFoundError as e:
            print(f"no such file or directory: {e}", file=sys.stderr)
            return 2
        findings: List[Finding] = []
        errors = 0
        for path in files:
            try:
                findings.extend(check_file(path, rules))
            except SyntaxError as e:
                errors += 1
                print(f"{path}: parse error: {e}", file=sys.stderr)
        parsed_n, cached_n = len(files), 0
    else:
        try:
            result = engine_mod.check_project(
                args.paths, rules=rules,
                cache_path=None if args.no_cache else args.cache)
        except FileNotFoundError as e:
            print(f"no such file or directory: {e}", file=sys.stderr)
            return 2
        findings, errors = result.findings, result.errors
        files = result.files
        parsed_n, cached_n = result.parsed, result.cached
        lifecycle_stats = result.lifecycle_stats
        shape_stats = result.shape_stats
        concurrency_stats = result.concurrency_stats
        if args.diff:
            changed = _git_changed_files(args.diff)
            if changed is None:
                return 2
            scope = engine_mod.reverse_dependency_closure(
                result.index, changed)
            findings = [f for f in findings
                        if os.path.abspath(f.path) in scope]
            files = [p for p in files if os.path.abspath(p) in scope]
            diff_note = (f" (diff vs {args.diff}: {len(changed)} "
                         f"changed, {len(files)} in closure)")
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        baseline_mod.write(args.write_baseline, findings)
        print(f"graftcheck: wrote baseline with {len(findings)} "
              f"finding{'s' if len(findings) != 1 else ''} to "
              f"{args.write_baseline}")
        return 0
    if args.baseline:
        findings = baseline_mod.filter_findings(findings, args.baseline)

    if args.sarif:
        doc = sarif_mod.to_sarif(findings)
        if args.sarif == "-":
            json.dump(doc, sys.stdout, indent=2)
            print()
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2)
                fh.write("\n")

    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    elif not (args.sarif == "-"):
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"graftcheck: {n} finding{'s' if n != 1 else ''} "
              f"in {len(files)} file{'s' if len(files) != 1 else ''}"
              f"{diff_note}")
    if args.stats:
        print(f"graftcheck: {elapsed:.2f}s ({parsed_n} parsed, "
              f"{cached_n} from cache)", file=sys.stderr)
        if lifecycle_stats:
            # analysis-cost counters for the CFG/fixpoint pass so cost
            # regressions are visible in CI logs (scripts/lint.sh)
            ls = lifecycle_stats
            print("graftcheck lifecycle: "
                  f"{ls.get('fns_analyzed', 0)} fns analyzed "
                  f"({ls.get('fns_total', 0)} seen, "
                  f"{ls.get('fns_trivial', 0)} trivial, "
                  f"{ls.get('fns_generators_skipped', 0)} generators, "
                  f"{ls.get('fns_too_large', 0)} over-budget, "
                  f"{ls.get('fns_errors', 0)} errors), "
                  f"{ls.get('cfg_nodes', 0)} cfg nodes, "
                  f"{ls.get('resources', 0)} resources, "
                  f"{ls.get('fixpoint_iterations', 0)} fixpoint "
                  f"iterations, "
                  f"{ls.get('fns_nonconverged', 0)} non-converged",
                  file=sys.stderr)
        if shape_stats:
            ss = shape_stats
            print("graftcheck shapes: "
                  f"{ss.get('fns_analyzed', 0)} fns analyzed "
                  f"({ss.get('fns_total', 0)} seen, "
                  f"{ss.get('fns_trivial', 0)} trivial, "
                  f"{ss.get('fns_errors', 0)} errors), "
                  f"{ss.get('pallas_sites', 0)} pallas sites, "
                  f"{ss.get('contraction_fns', 0)} contraction fns, "
                  f"{ss.get('sites_shaped', 0)} sites shaped, "
                  f"{ss.get('cfg_nodes', 0)} cfg nodes, "
                  f"{ss.get('fixpoint_iterations', 0)} fixpoint "
                  f"iterations, "
                  f"{ss.get('fns_nonconverged', 0)} non-converged",
                  file=sys.stderr)
        if concurrency_stats:
            cs = concurrency_stats
            print("graftcheck concurrency: "
                  f"{cs.get('fns_analyzed', 0)} fns analyzed "
                  f"({cs.get('fns_total', 0)} seen, "
                  f"{cs.get('fns_generators_skipped', 0)} generators, "
                  f"{cs.get('fns_cfg_skipped', 0)} over-budget, "
                  f"{cs.get('fns_errors', 0)} errors), "
                  f"{cs.get('classes_with_locks', 0)} classes with "
                  f"locks, {cs.get('locks_discovered', 0)} locks, "
                  f"{cs.get('guards_inferred', 0)} guards inferred, "
                  f"{cs.get('held_states', 0)} held-lock states, "
                  f"{cs.get('helper_reruns', 0)} helper re-runs",
                  file=sys.stderr)
    if errors:
        return 2
    return 1 if findings else 0


def _git_changed_files(ref: str) -> Optional[List[str]]:
    """Changed-vs-REF .py files as absolute paths (working tree
    included, so a pre-push hook sees uncommitted edits); None on git
    failure."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True).stdout.strip()
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        msg = getattr(e, "stderr", "") or str(e)
        print(f"graftcheck: git diff vs {ref!r} failed: {msg.strip()}",
              file=sys.stderr)
        return None
    return [os.path.join(top, line) for line in out.splitlines()
            if line.endswith(".py")]


# ---------------------------------------------------------------------------
# graph


def _graph_main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.graftcheck graph",
        description="dump the remote call graph (tasks, actor methods, "
                    "submit/sync-get/bind edges) as GraphViz DOT")
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument("--out", metavar="FILE", default="-",
                        help="output path (default: stdout)")
    parser.add_argument("--cache", metavar="FILE",
                        default=engine_mod.default_cache_path())
    parser.add_argument("--no-cache", action="store_true")
    args = parser.parse_args(argv)

    try:
        result = engine_mod.check_project(
            args.paths, rules=set(),
            cache_path=None if args.no_cache else args.cache)
    except FileNotFoundError as e:
        print(f"no such file or directory: {e}", file=sys.stderr)
        return 2
    dot = engine_mod.to_dot(result.graph)
    if args.out == "-":
        sys.stdout.write(dot)
    else:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(dot)
        print(f"graftcheck: wrote {len(result.graph.nodes)} nodes / "
              f"{len(result.graph.edges)} edges to {args.out}")
    return 2 if result.errors else 0


# ---------------------------------------------------------------------------
# locks


def _locks_main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.graftcheck locks",
        description="dump the static role-level lock-order graph "
                    "(nested held-lock states + transitive acquires); "
                    "the dynamic RAY_TPU_DEBUG_LOCKS=1 order graph must "
                    "be a subgraph of this (scripts/locks_gate.py)")
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument("--dot", action="store_true",
                        help="emit GraphViz DOT instead of text")
    parser.add_argument("--json", action="store_true",
                        help="emit JSON ({roles: [...], edges: [{src, "
                             "dst, path, line, via}]})")
    parser.add_argument("--out", metavar="FILE", default="-",
                        help="output path (default: stdout)")
    parser.add_argument("--cache", metavar="FILE",
                        default=engine_mod.default_cache_path())
    parser.add_argument("--no-cache", action="store_true")
    args = parser.parse_args(argv)

    from . import rules_concurrency

    try:
        result = engine_mod.check_project(
            args.paths, rules=set(),
            cache_path=None if args.no_cache else args.cache)
    except FileNotFoundError as e:
        print(f"no such file or directory: {e}", file=sys.stderr)
        return 2
    edges = rules_concurrency.build_lock_order_graph(result.index)
    items = sorted((src, dst, path, line, via)
                   for (src, dst), (path, line, via) in edges.items())
    if args.json:
        out = json.dumps({
            "roles": rules_concurrency.project_lock_roles(result.index),
            "edges": [
                {"src": s, "dst": d, "path": p, "line": ln, "via": v}
                for s, d, p, ln, v in items]}, indent=2) + "\n"
    elif args.dot:
        lines = ["digraph lock_order {", "  rankdir=LR;"]
        for s, d, p, ln, v in items:
            note = f" {v}" if v else ""
            lines.append(f'  "{s}" -> "{d}" '
                         f'[label="{p}:{ln}{note}"];')
        lines.append("}")
        out = "\n".join(lines) + "\n"
    else:
        out = "".join(
            f"{s} -> {d}  ({p}:{ln}{' ' + v if v else ''})\n"
            for s, d, p, ln, v in items)
        out += (f"graftcheck locks: {len(items)} order "
                f"edge{'s' if len(items) != 1 else ''}\n")
    if args.out == "-":
        sys.stdout.write(out)
    else:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(out)
        print(f"graftcheck: wrote {len(items)} lock-order edges to "
              f"{args.out}")
    return 2 if result.errors else 0
