"""Whole-program distributed-correctness rules.

These passes run over the :class:`~.engine.ProjectIndex` (cached file
summaries — no AST access), every run:

GC010
    Actor-deadlock detection: cycles of *synchronous* ``get()`` waits
    through the remote call graph. A cycle of actor methods that each
    block on the next deadlocks the moment the calls coincide — every
    actor in the cycle is parked in ``get()`` and cannot serve the
    incoming call that would unblock it. Self-calls on
    single-concurrency actors are the 1-cycle special case. Cycles
    touching an actor created with ``max_concurrency > 1`` anywhere in
    the project are skipped (a second thread can serve the call).

GC011
    Interprocedural serialization flow: a known-unserializable value
    (lock, socket, file handle, thread, ...) flowing into ``.remote()``
    arguments or out of a task return — including values laundered
    through helper functions (``f.remote(make_lock())`` where
    ``make_lock`` returns ``threading.Lock()`` two modules away).

GC001/GC003 (interprocedural upgrade)
    The local rules only see blocking ``get()`` / global mutation
    lexically inside the remote body. Here we follow plain calls one
    level deep: a remote function calling a project-local helper that
    blocks or mutates module globals gets flagged at the call site.
    Helpers whose own ``get()`` line carries a GC001 suppression are
    treated as reviewed and stay silent.

GC008 (call-graph resolution)
    Replaces the module-local name-matching heuristic: bind receivers
    are resolved through the project index (handle variables, list-of-
    handle loop vars, ``self.<attr>`` bindings, imports), so a
    same-named method on an unrelated actor class is no longer flagged.
    Unresolvable receivers keep the conservative name-wide fallback.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .engine import (CallGraph, Edge, ProjectIndex, resolve_call_target,
                     resolve_submit_target)
from .local import Finding
from .summary import suppressed


def run(index: ProjectIndex, graph: CallGraph,
        enabled: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    if "GC010" in enabled:
        out.extend(_gc010(index, graph))
    if "GC011" in enabled:
        out.extend(_gc011(index))
    if "GC001" in enabled or "GC003" in enabled:
        out.extend(_interprocedural(index, enabled))
    if "GC008" in enabled:
        out.extend(_gc008(index))
    return out


# ---------------------------------------------------------------------------
# GC010 — synchronous wait cycles


def _gc010(index: ProjectIndex, graph: CallGraph) -> List[Finding]:
    adj = graph.sync_adj()
    nodes = set(adj)
    for edges in adj.values():
        nodes.update(e.dst for e in edges)

    # Tarjan SCC (iterative)
    idx_of: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(adj.get(v0, ())))]
        idx_of[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for e in it:
                w = e.dst
                if w not in idx_of:
                    idx_of[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], idx_of[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx_of[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

    for v in sorted(nodes):
        if v not in idx_of:
            strongconnect(v)

    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    for comp in sccs:
        comp_set = set(comp)
        cyclic = len(comp) > 1 or any(
            e.dst == comp[0] for e in adj.get(comp[0], ()))
        if not cyclic:
            continue
        cycle = _extract_cycle(adj, comp_set)
        if not cycle:
            continue
        key = _canonical_cycle_key(cycle)
        if key in seen_cycles:
            continue
        seen_cycles.add(key)
        findings.extend(_report_cycle(index, graph, cycle))
    return findings


def _extract_cycle(adj: Dict[str, List[Edge]],
                   comp: Set[str]) -> Optional[List[Edge]]:
    """One elementary cycle inside an SCC (DFS back to the start)."""
    start = sorted(comp)[0]
    path: List[Edge] = []
    visited: Set[str] = set()

    def dfs(v: str) -> bool:
        for e in sorted(adj.get(v, ()), key=lambda e: (e.dst, e.line)):
            if e.dst not in comp:
                continue
            if e.dst == start:
                path.append(e)
                return True
            if e.dst in visited:
                continue
            visited.add(e.dst)
            path.append(e)
            if dfs(e.dst):
                return True
            path.pop()
        return False

    visited.add(start)
    return path if dfs(start) else None


def _canonical_cycle_key(cycle: Sequence[Edge]) -> Tuple[str, ...]:
    names = [e.src for e in cycle]
    rotations = [tuple(names[i:] + names[:i]) for i in range(len(names))]
    return min(rotations)


def _report_cycle(index: ProjectIndex, graph: CallGraph,
                  cycle: List[Edge]) -> List[Finding]:
    # at least one hop must be an actor method: task-only recursion is
    # GC001's territory (bounded nesting is supported)
    classes: Set[str] = set()
    any_actor = False
    for e in cycle:
        info = graph.nodes.get(e.dst, {})
        if info.get("actor_method"):
            any_actor = True
        if info.get("cls"):
            classes.add(info["cls"])
        src_info = graph.nodes.get(e.src, {})
        if src_info.get("cls"):
            classes.add(src_info["cls"])
    if not any_actor:
        return []
    if not all(index.single_concurrency(c) for c in classes):
        return []
    # annotating any edge of the cycle acknowledges the whole cycle
    for e in cycle:
        s = _summary_for_path(index, e.path)
        if s is not None and suppressed(s, e.line, "GC010"):
            return []
    hops = " -> ".join(
        f"{e.dst} ({e.path}:{e.line})" for e in cycle)
    first = cycle[0]
    concurrency_note = "single-concurrency " if len(cycle) == 1 else ""
    return [Finding(
        path=first.path, line=first.line, col=1, rule="GC010",
        message=f"synchronous get() wait cycle through the remote call "
                f"graph: {first.src} ({first.path}:{first.line}) -> {hops}; "
                f"each hop blocks in get() while the {concurrency_note}"
                f"callee needs the caller to return — this deadlocks when "
                f"the calls coincide. Break the cycle with async waits, "
                f"ref-passing, or max_concurrency > 1")]


def _summary_for_path(index: ProjectIndex,
                      path: str) -> Optional[Dict[str, Any]]:
    for s in index.summaries:
        if s["path"] == path:
            return s
    return None


# ---------------------------------------------------------------------------
# GC011 — serialization flow


def _returns_unserializable(index: ProjectIndex) -> Dict[str, str]:
    """Fixpoint: fq -> unserializable kind for functions whose return
    value cannot ride the wire (directly or through helpers)."""
    out: Dict[str, str] = {}
    for _ in range(4):   # call chains deeper than 4 don't happen here
        changed = False
        for fq, (s, fn) in index.functions.items():
            if fq in out:
                continue
            for p in fn["returns"]:
                kind = _prov_unser_kind(index, s, fn, p, out)
                if kind:
                    out[fq] = kind
                    changed = True
                    break
        if not changed:
            break
    return out


def _prov_unser_kind(index: ProjectIndex, summary: Dict[str, Any],
                     fn: Dict[str, Any], prov: Dict[str, Any],
                     returns_map: Dict[str, str]) -> Optional[str]:
    if prov["kind"] == "ctor":
        return prov["ctor"]
    if prov["kind"] == "var":
        direct = fn["local_unser"].get(prov["name"]) \
            or summary["module_unser"].get(prov["name"])
        if direct:
            return direct
        # var assigned from a helper call: lk = make_lock()
        callee_name = fn.get("call_assigns", {}).get(prov["name"])
        if callee_name:
            callee = _resolve_call(index, summary, fn, callee_name)
            if callee:
                return returns_map.get(callee)
        return None
    if prov["kind"] == "call" and prov.get("name"):
        callee = _resolve_call(index, summary, fn, prov["name"])
        if callee:
            return returns_map.get(callee)
    return None


_resolve_call = resolve_call_target


def _gc011(index: ProjectIndex) -> List[Finding]:
    returns_map = _returns_unserializable(index)
    findings: List[Finding] = []
    for fq, (s, fn) in index.functions.items():
        # (a) unserializable values flowing into .remote() args
        for sub in fn["submits"]:
            if "GC011" in sub["suppress"]:
                continue
            provs = list(enumerate(sub["args"])) + \
                [(k, v) for k, v in sub["kwargs"].items()]
            for pos, p in provs:
                kind = _prov_unser_kind(index, s, fn, p, returns_map)
                if not kind:
                    continue
                via = ""
                if p["kind"] == "call":
                    callee = _resolve_call(index, s, fn, p["name"])
                    loc = ""
                    if callee:
                        cs, cfn = index.functions[callee]
                        loc = f" ({cs['path']}:{cfn['lineno']})"
                    via = f" via helper {p['name']}(){loc}"
                elif p["kind"] == "var":
                    via = f" via '{p['name']}'"
                findings.append(Finding(
                    path=s["path"], line=sub["lineno"], col=sub["col"],
                    rule="GC011",
                    message=f"argument {pos} of this .remote() call is a "
                            f"{kind}{via}; it cannot be serialized to a "
                            f"worker — create it inside the task or hold "
                            f"it in an actor"))
        # (b) remote functions / actor methods returning unserializable.
        # Nested closures inside actor methods inherit is_remote for the
        # other passes but their returns don't cross the wire — only the
        # method itself ("Cls.m", depth 1) serializes its return value.
        if not fn["is_remote"]:
            continue
        if fn.get("cls") and fn["qname"].count(".") != 1:
            continue
        for p in fn["returns"]:
            kind = _prov_unser_kind(index, s, fn, p, returns_map)
            if not kind:
                continue
            line = p.get("lineno", fn["lineno"])
            if suppressed(s, line, "GC011"):
                continue
            via = f" via helper {p['name']}()" if p["kind"] == "call" else ""
            findings.append(Finding(
                path=s["path"], line=line, col=1, rule="GC011",
                message=f"remote {fn['qname']} returns a {kind}{via}; task "
                        f"returns must be serializable — return a handle "
                        f"or plain data instead"))
    return findings


# ---------------------------------------------------------------------------
# interprocedural GC001 / GC003 (one level deep)


def _interprocedural(index: ProjectIndex,
                     enabled: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    for fq, (s, fn) in index.functions.items():
        if not fn["is_remote"]:
            continue
        for call in fn["calls"]:
            callee = _resolve_call(index, s, fn, call["name"])
            if callee is None or callee == fq:
                continue
            cs, cfn = index.functions[callee]
            if cfn["is_remote"]:
                continue   # direct remote-body gets are the local rule's job
            if "GC001" in enabled and "GC001" not in call["suppress"]:
                hot = [g for g in cfn["gets"]
                       if "GC001" not in g["suppress"]]
                if hot:
                    g0 = hot[0]
                    findings.append(Finding(
                        path=s["path"], line=call["lineno"],
                        col=call["col"], rule="GC001",
                        message=f"helper {call['name']}() blocks in get() "
                                f"at {cs['path']}:{g0['lineno']} and is "
                                f"called from remote {fn['qname']} — same "
                                f"nested-get deadlock risk as a direct "
                                f"get() (interprocedural, one level)"))
            if "GC003" in enabled and "GC003" not in call["suppress"] \
                    and cfn["global_writes"]:
                findings.append(Finding(
                    path=s["path"], line=call["lineno"], col=call["col"],
                    rule="GC003",
                    message=f"helper {call['name']}() "
                            f"({cs['path']}:{cfn['lineno']}) mutates module "
                            f"global(s) {', '.join(cfn['global_writes'])} "
                            f"and is called from remote {fn['qname']}; the "
                            f"write lands in the worker process and is "
                            f"lost (interprocedural, one level)"))
    return findings


# ---------------------------------------------------------------------------
# GC008 — call-graph-resolved compiled-graph binding


_GC008_REMOTE_MSG = (
    "dynamic .remote() submission inside a method bound into a compiled "
    "graph reintroduces per-call scheduling and can deadlock against the "
    "resident loop; keep bound methods pure compute and do dynamic work "
    "outside the graph")
_GC008_GET_MSG = (
    "blocking get() inside a method bound into a compiled graph stalls "
    "the resident loop (and every downstream stage) on the dynamic task "
    "plane; pass the value through the graph's channels instead")


def _gc008(index: ProjectIndex) -> List[Finding]:
    resolved: Set[Tuple[str, str]] = set()     # (cls_fq, method)
    fallback: Set[str] = set()                 # method names, name-wide
    for s in index.summaries:
        for b in s["bind_sites"]:
            if b.get("resolved") and b.get("cls"):
                cls_fq = index.resolve_class(s, b["cls"])
                if cls_fq is not None:
                    resolved.add((cls_fq, b["method"]))
                    continue
            fallback.add(b["method"])

    findings: List[Finding] = []
    for fq, (s, fn) in index.functions.items():
        cls = fn.get("cls")
        if not cls:
            continue
        crec = s["classes"].get(cls)
        if not crec or not crec["is_actor"]:
            continue
        # "Cls.method" or nested "Cls.method.inner" — the bound method is
        # the first component after the class name
        qparts = fn["qname"].split(".")
        if len(qparts) < 2:
            continue
        method = qparts[1]
        cls_fq = f"{s['module']}.{cls}"
        if (cls_fq, method) not in resolved and method not in fallback:
            continue
        for sub in fn["submits"]:
            if "GC008" in sub["suppress"]:
                continue
            findings.append(Finding(
                path=s["path"], line=sub["lineno"], col=sub["col"],
                rule="GC008", message=_GC008_REMOTE_MSG))
        for g in fn["gets"]:
            if "GC008" in g["suppress"]:
                continue
            findings.append(Finding(
                path=s["path"], line=g["lineno"], col=g["col"],
                rule="GC008", message=_GC008_GET_MSG))
    return findings
