"""TPU/SPMD sharding-consistency rules (GC020 series).

Mis-sharded SPMD code rarely fails loudly: a collective over an axis
the enclosing mesh never bound either errors deep inside XLA lowering
or — worse — silently materializes cross-replica transfers. These
passes check ``shard_map`` discipline statically, over the project
index (so a mesh defined in a ``mesh.py``-style module and a kernel in
another file still line up):

GC020
    A collective (``psum``/``pmean``/``ppermute``/``pvary``/
    ``axis_index``/...) inside a shard-mapped function names an axis
    that is not bound by the enclosing ``axis_names=`` set or the mesh's
    axis names. Symbolic axes are matched by symbol (``pp_axis`` in the
    body vs ``axis_names=frozenset({pp_axis})``) and through module-
    level string constants; anything unresolvable stays silent —
    the rule only fires when both sides are fully known.

GC021
    ``in_specs`` arity mismatched to the wrapped function's signature:
    ``shard_map(f, in_specs=(a, b))`` where ``f`` takes three required
    arguments fails at trace time with a pytree error that names
    neither side. Resolves local defs, imported project functions,
    ``functools.partial`` (bound positionals + keywords), and lambdas.

Sites are collected from direct ``shard_map(...)`` calls, from the
repo's ``lower_shard_map(...)``/``lower_jit(...)`` wrappers in
``parallel/sharding/lower.py`` (specs are keyword-only there), and
from ``functools.partial(shard_map, ...)`` bindings applied later —
the summary extractor synthesizes a site from the merged arguments.
``lower_jit`` sites carry no axis binding, so only GC021 applies.

(GC022, the donated-buffer read, moved onto the CFG in v4 — see
:mod:`.rules_shapes`.)

Only calls that resolve to the real ``shard_map`` (``jax.shard_map``,
``jax.experimental.shard_map.shard_map``, or the repo's
``ray_tpu.jax_compat.shard_map`` shim) or to the repo's lowering
wrappers are checked; Pallas ``in_specs=[pl.BlockSpec...]`` grids
never match.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from .engine import (LOWER_JIT_FQS, LOWER_SHARD_MAP_FQS, SHARD_MAP_FQS,
                     ProjectIndex)
from .local import Finding


def run(index: ProjectIndex, enabled: Set[str]) -> List[Finding]:
    if not ({"GC020", "GC021"} & enabled):
        return []
    out: List[Finding] = []
    for s in index.summaries:
        for site in s["shardmap"]:
            if not _is_real_shard_map(index, s, site):
                continue
            target = _resolve_wrapped(index, s, site)
            if "GC021" in enabled and "GC021" not in site["suppress"]:
                out.extend(_gc021(s, site, target))
            if "GC020" in enabled \
                    and site.get("wrapper") != "lower_jit":
                out.extend(_gc020(index, s, site, target))
    return out


def _is_real_shard_map(index: ProjectIndex, summary: Dict[str, Any],
                       site: Dict[str, Any]) -> bool:
    fq = index.resolve(summary, site["callee"])
    wrapper = site.get("wrapper", "shard_map")
    if wrapper == "lower_shard_map":
        return fq in LOWER_SHARD_MAP_FQS
    if wrapper == "lower_jit":
        return fq in LOWER_JIT_FQS
    return fq in SHARD_MAP_FQS


def _resolve_wrapped(index: ProjectIndex, summary: Dict[str, Any],
                     site: Dict[str, Any]
                     ) -> Optional[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """The wrapped function's (summary, fn record), resolving nested
    defs in the enclosing scope, module-level defs, and imports."""
    fnref = site["fn"]
    if fnref["kind"] not in ("name", "partial"):
        return None
    name = fnref["name"]
    if not name or name.startswith("self."):
        return None
    # nested def in the enclosing scope: encl qname + "." + name
    encl = site["encl"]
    if encl and encl != "<module>":
        cand = f"{encl}.{name}"
        if cand in summary["functions"]:
            return summary, summary["functions"][cand]
    if name in summary["functions"]:
        return summary, summary["functions"][name]
    fq = index.resolve_function(summary, name)
    return index.functions[fq] if fq else None


# ---------------------------------------------------------------------------
# GC021 — in_specs arity


def _gc021(summary: Dict[str, Any], site: Dict[str, Any],
           target: Optional[Tuple[Dict[str, Any], Dict[str, Any]]]
           ) -> List[Finding]:
    arity = site["in_specs_arity"]
    if arity is None:
        return []
    fnref = site["fn"]
    if fnref["kind"] == "lambda":
        lo = fnref["nparams"] - fnref["ndefaults"]
        hi = None if fnref["vararg"] else fnref["nparams"]
        desc = "lambda"
    elif target is not None:
        ts, tfn = target
        if tfn.get("cls"):
            return []   # bound methods: `self` skews the count
        params = list(tfn["params"])
        n_def = tfn["n_defaults"]
        defaulted = set(params[len(params) - n_def:]) if n_def else set()
        if fnref["kind"] == "partial":
            params = params[fnref["npos"]:]
            params = [p for p in params if p not in set(fnref["kw"])]
            defaulted = {p for p in defaulted if p in params}
        lo = len(params) - len(defaulted)
        hi = None if tfn["has_vararg"] else len(params)
        desc = f"{tfn['qname']}() ({ts['path']}:{tfn['lineno']})"
    else:
        return []
    if arity < lo or (hi is not None and arity > hi):
        want = str(lo) if hi == lo else \
            (f"{lo}..{hi}" if hi is not None else f">= {lo}")
        return [Finding(
            path=summary["path"], line=site["lineno"], col=1, rule="GC021",
            message=f"shard_map in_specs has {arity} "
                    f"entr{'y' if arity == 1 else 'ies'} but the wrapped "
                    f"{desc} takes {want} positional argument(s); the "
                    f"mismatch fails at trace time with an opaque pytree "
                    f"error — make in_specs match the call arity")]
    return []


# ---------------------------------------------------------------------------
# GC020 — unbound collective axes


def _bound_axes(index: ProjectIndex, summary: Dict[str, Any],
                site: Dict[str, Any]
                ) -> Optional[Tuple[Set[str], Set[str]]]:
    """-> (literal axis names, unresolved symbolic names) bound by this
    shard_map site, or None when the bound set is unknowable."""
    lits: Set[str] = set()
    syms: Set[str] = set()
    if site["axis_given"]:
        ax = site["axis"]
        if ax is None or not ax["clean"]:
            return None
        lits.update(ax["lits"])
        for sym in ax["syms"]:
            const = index.lookup_str_const(summary, sym)
            if const is not None:
                lits.add(const)
                continue
            axes = index.lookup_mesh_axes(summary, sym)
            if axes is not None:
                lits.update(axes)
                continue
            syms.add(sym)
        return lits, syms
    # no axis_names=: manual over every mesh axis — need the mesh
    if site["mesh"]:
        axes = index.lookup_mesh_axes(summary, site["mesh"])
        if axes is not None:
            return set(axes), set()
    return None


def _gc020(index: ProjectIndex, summary: Dict[str, Any],
           site: Dict[str, Any],
           target: Optional[Tuple[Dict[str, Any], Dict[str, Any]]]
           ) -> List[Finding]:
    if target is None:
        return []
    bound = _bound_axes(index, summary, site)
    if bound is None:
        return []
    bound_lits, bound_syms = bound
    ts, tfn = target
    tq = tfn["qname"]
    findings: List[Finding] = []
    for coll in ts["collectives"]:
        if coll["encl"] != tq and not coll["encl"].startswith(tq + "."):
            continue
        if "GC020" in coll["suppress"] or "GC020" in site["suppress"]:
            continue
        ax = coll["axis"]
        if ax is None or not ax["clean"]:
            continue
        if not _is_real_collective(index, ts, coll):
            continue
        bad: List[str] = []
        if not bound_syms:
            # fully literal bound set: literals must be members, symbols
            # must resolve to members
            for lit in ax["lits"]:
                if lit not in bound_lits:
                    bad.append(lit)
            for sym in ax["syms"]:
                const = index.lookup_str_const(ts, sym)
                if const is not None and const not in bound_lits:
                    bad.append(f"{sym}={const!r}")
        else:
            # symbolic bound set: only symbol-by-symbol matches are
            # provable; unknown symbols/literals stay silent
            for sym in ax["syms"]:
                if sym not in bound_syms:
                    const = index.lookup_str_const(ts, sym)
                    if const is not None and const not in bound_lits:
                        bad.append(f"{sym}={const!r}")
        if not bad:
            continue
        bound_desc = ", ".join(sorted(bound_lits)
                               + [f"<{x}>" for x in sorted(bound_syms)])
        findings.append(Finding(
            path=ts["path"], line=coll["lineno"], col=coll["col"],
            rule="GC020",
            message=f"collective {coll['op']}() names axis "
                    f"{', '.join(repr(b) for b in bad)} which is not "
                    f"bound by the enclosing shard_map at "
                    f"{summary['path']}:{site['lineno']} (bound axes: "
                    f"{bound_desc or 'none'}); unbound axes fail at "
                    f"lowering or silently change collective scope"))
    return findings


def _is_real_collective(index: ProjectIndex, summary: Dict[str, Any],
                        coll: Dict[str, Any]) -> bool:
    d = coll["dotted"]
    if "." in d:
        parts = d.split(".")
        return "lax" in parts[:-1]
    fq = index.resolve(summary, d)
    return "jax" in fq.split(".")[0] or ".jax_compat." in fq \
        or fq.startswith("ray_tpu.jax_compat")
