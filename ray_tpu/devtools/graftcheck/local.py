"""graftcheck per-file analysis — the single-module half of the linter.

An AST-based checker (stdlib ``ast`` only) whose rules encode the
correctness hazards this runtime shares with the reference framework —
hazards a generic linter cannot see because they depend on what
``@remote`` means. This module owns the rules that are decidable from
one file alone (GC001-GC008); the whole-program rules (GC010/GC011,
the GC020 SPMD series, and the call-graph-resolved GC008 upgrade) live
in :mod:`.summary` / :mod:`.engine` / :mod:`.rules_project` /
:mod:`.rules_spmd`, the CFG-based path-sensitive lifecycle family
(GC030-GC033) in :mod:`.cfg` / :mod:`.dataflow` /
:mod:`.rules_lifecycle`, and the shape-and-spec family (GC040-GC044
plus the CFG'd GC022) in :mod:`.shapes` / :mod:`.rules_shapes`; all
run over the project index. The package
``__init__`` composes all layers behind the same ``check_source`` /
``check_file`` API the single-file linter always had.

====== =================================================================
GC001  blocking ``get()`` (``ray_tpu.get`` / ``runtime.get`` /
       ``ref.get()``) inside a ``@remote`` function or actor method body
       — nested-task deadlock risk when the pool is saturated
GC002  capture of a known-unserializable module-level object (lock,
       condition, file handle, socket, thread) in a remote closure —
       fails at submission time, or worse, pickles stale state
GC003  mutation of a module-level global from a task body — the write
       lands in the *worker* process and silently never propagates
GC004  ``time.sleep`` inside an ``async def`` — blocks the actor event
       loop (use ``await asyncio.sleep``)
GC005  bare ``except:`` that never re-raises — swallows ``TaskError`` /
       ``ActorDiedError`` / ``SystemExit`` and hides worker death
GC006  ``lock.acquire()`` outside ``with``/try-finally — the lock leaks
       on any exception path and wedges every later acquirer
GC007  bare ``print()`` in ``ray_tpu`` library code — un-attributed,
       un-queryable output; route it through the structured logger
       (``ray_tpu.util.logs.get_logger``) so it reaches the cluster log
       store with task attribution. User-facing surfaces (CLI,
       dashboard, devtools, examples, tests, scripts) are exempt by
       path; load-bearing prints take a line suppression.
GC008  blocking ``get()`` or dynamic ``.remote()`` submission inside an
       actor method that is bound into a compiled graph
       (``X.method.bind(...)`` elsewhere in the module) — the compiled
       graph's resident loop executes these methods with NO scheduler
       behind them; dynamic calls reintroduce per-call RPC/scheduling
       and can deadlock against the loop. Keep bound methods pure
       compute; do dynamic work outside the graph.
GC009  blocking ``ray_tpu.get()`` or synchronous handle resolution
       (``handle.remote(...).result()``) inside an ``async def`` method
       of a ``@serve.deployment`` class — stalls the replica's event
       loop for every queued request; ``await`` the response (or hop to
       an executor) instead.
GC012  unbounded bare retry loop: ``while True:`` wrapping a
       try/except whose handler swallows-and-retries a remote call or
       connection attempt, with no backoff growth, no deadline, and no
       attempt budget anywhere in the loop — hammers a dead peer
       forever and turns one fault into a spin. Route the loop through
       ``ray_tpu.util.retry`` (RetryPolicy / call_with_retry) or add an
       explicit deadline/attempt bound.
====== =================================================================

Suppression: append ``# graftcheck: disable=GC001`` (comma-separate for
several rules, or ``disable=all``) to the flagged line or put it alone
on the line above. ``# graftcheck: disable-file=GC005`` anywhere in a
file suppresses that rule file-wide.

CLI (see :mod:`.cli`)::

    python -m ray_tpu.devtools.graftcheck [--json] [--sarif F] [--baseline F] paths...
    python -m ray_tpu.devtools.graftcheck graph paths...

Exit status: 0 = clean, 1 = findings, 2 = usage/parse errors only.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "GC001": "blocking get() inside a @remote function or actor method "
             "(nested-task deadlock risk)",
    "GC002": "remote closure captures a known-unserializable module-level "
             "object",
    "GC003": "module-level global mutated from a task body (the write stays "
             "in the worker process)",
    "GC004": "blocking time.sleep() in an async function (blocks the actor "
             "event loop; use await asyncio.sleep)",
    "GC005": "bare except: without re-raise swallows TaskError/"
             "ActorDiedError/SystemExit",
    "GC006": "lock.acquire() without with-statement or try/finally release "
             "(leaks the lock on exception paths)",
    "GC007": "bare print() in library code (use the structured logger "
             "ray_tpu.util.logs.get_logger so output is attributed and "
             "queryable)",
    "GC008": "blocking get() or dynamic .remote() inside a method bound "
             "into a compiled graph (static graphs must stay static)",
    "GC009": "blocking get()/.result() inside an async serve deployment "
             "method (stalls the replica event loop for every queued "
             "request)",
    "GC012": "unbounded bare retry loop around a remote call / connect "
             "(no backoff, deadline, or attempt budget — use "
             "ray_tpu.util.retry)",
    # whole-program rules (engine-backed; see rules_project.py/rules_spmd.py)
    "GC010": "actor-deadlock: cycle of synchronous get() waits through the "
             "remote call graph (incl. self-calls on single-concurrency "
             "actors)",
    "GC011": "known-unserializable value (lock/socket/file/thread) flows "
             "into .remote() args or a task return, possibly through "
             "helper functions",
    "GC020": "collective (psum/pmean/ppermute/...) names an axis not bound "
             "by the enclosing shard_map mesh/axis_names",
    "GC021": "shard_map in_specs arity does not match the wrapped "
             "function's signature",
    "GC022": "buffer donated via donate_argnums is read after the jitted "
             "call (its memory was reused by XLA); path-sensitive — only "
             "paths through the donating call fire",
    # CFG-based path-sensitive lifecycle rules (engine-backed; see
    # cfg.py/dataflow.py/rules_lifecycle.py)
    "GC030": "resource leak: an acquired resource (pool alloc/retain, "
             "channel segment, collective group, lock.acquire, open()) "
             "reaches a function exit unreleased on some path",
    "GC031": "double-release / use-after-release of a resource along "
             "some path",
    "GC032": "resource release skipped by a swallowing except: an "
             "exception before the release rejoins the normal flow with "
             "the resource still held",
    "GC033": "conditional acquire with unconditional release (or vice "
             "versa): the release runs on paths where the acquire never "
             "did",
    # shape-and-spec abstract interpretation (v4; see shapes.py /
    # rules_shapes.py — GC022 also lives there now, on the CFG)
    "GC040": "mesh-axis divisibility: an in_specs entry shards a dim "
             "whose statically-known size the bound mesh axis size does "
             "not divide — GSPMD pads every shard silently",
    "GC041": "sharded contraction dim: a dot_general/einsum/matmul "
             "contraction dim of the shard_mapped function carries a "
             "non-None spec entry (SpecLayout rule: contraction dims "
             "never shard) — per-shard partial sums without a psum",
    "GC042": "Pallas kernel consistency: index_map arity vs grid rank, "
             "index_map return rank vs block_shape rank, kernel params "
             "vs wired refs, block divisibility and constant/identity "
             "out-of-bounds index maps, where every number resolves",
    "GC043": "codec pairing on wire paths: a quantized payload reaching "
             "a reduce before any dequantize (sums codewords, not "
             "values), or sent point-to-point in a module with no "
             "decode on any receive leg",
    "GC044": "collective geometry: a psum_scatter/all_to_all inside a "
             "shard_mapped body splits a per-shard dim the mesh axis "
             "size does not divide, where shapes, specs and mesh all "
             "resolve statically",
    # thread-aware concurrency analysis (v5; see rules_concurrency.py —
    # a held-lock MUST-state over the v3 CFG plus project-wide passes)
    "GC050": "guarded-by violation: a class attribute whose accesses "
             "majority-hold one specific lock is read or written on a "
             "path where no lock is held at all (stale-read / "
             "lost-update hazard)",
    "GC051": "lock-reentry hazard: a stored callback invoked under a "
             "held lock, a non-reentrant lock re-acquired while held, "
             "or a call to a method that transitively re-acquires a "
             "held non-reentrant lock (deadlock)",
    "GC052": "lock-order cycle: the project-wide static acquisition-"
             "order graph (nested held-lock states + transitive "
             "acquires) contains a strongly-connected component — the "
             "AB/BA deadlock precondition, every hop listed",
    "GC053": "blocking call under lock: a get()/recv()/Event.wait() "
             "with no timeout/Thread.join()/Queue.get() reached while "
             "any lock is held — one slow peer wedges every thread "
             "queued on the lock",
    "GC054": "non-atomic check-then-act: an Event.is_set()/dict-"
             "membership/attr-None test whose mutating counterpart "
             "runs on a path where the guard lock was released in "
             "between — two racing threads both pass the test",
}

# GC007 targets library code only: user-facing surfaces where print IS
# the product are exempt by path (basename or any path segment)
_GC007_EXEMPT_BASENAMES = {"cli.py", "dashboard.py", "__main__.py"}
_GC007_EXEMPT_SEGMENTS = {"examples", "devtools", "scripts", "tests",
                          "docs", "bench"}


def _gc007_exempt(path: str) -> bool:
    norm = path.replace("\\", "/")
    if os.path.basename(norm) in _GC007_EXEMPT_BASENAMES:
        return True
    return bool(_GC007_EXEMPT_SEGMENTS.intersection(norm.split("/")))

# module-level constructors whose results cannot ride a cloudpickle'd
# closure into a worker process
_UNSERIALIZABLE_CTORS: Dict[Tuple[str, ...], str] = {
    ("threading", "Lock"): "threading.Lock",
    ("threading", "RLock"): "threading.RLock",
    ("threading", "Condition"): "threading.Condition",
    ("threading", "Event"): "threading.Event",
    ("threading", "Semaphore"): "threading.Semaphore",
    ("threading", "Thread"): "threading.Thread",
    ("socket", "socket"): "socket.socket",
    ("socket", "create_connection"): "socket.create_connection",
    ("open",): "open() file handle",
    ("io", "open"): "open() file handle",
    ("subprocess", "Popen"): "subprocess.Popen",
    ("mmap", "mmap"): "mmap.mmap",
}

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>all|[Gg][Cc]\d{3}(?:\s*,\s*[Gg][Cc]\d{3})*)")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


# ---------------------------------------------------------------------------
# suppression comments


def _parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """-> ({line: {rules}}, file_wide_rules). 'all' expands to every rule."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        raw = m.group("rules").strip()
        rules = (set(RULES) if raw == "all"
                 else {r.strip().upper() for r in raw.split(",") if r.strip()})
        if m.group("scope"):
            file_wide |= rules
        else:
            per_line.setdefault(lineno, set()).update(rules)
            if text.strip().startswith("#"):
                # a standalone suppression comment also covers the next line
                per_line.setdefault(lineno + 1, set()).update(rules)
    return per_line, file_wide


# ---------------------------------------------------------------------------
# AST helpers


def _dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('a','b','c') for a.b.c / ('f',) for f; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _is_remote_decorator(dec: ast.AST) -> bool:
    """@remote / @ray_tpu.remote / @ray.remote, bare or called, plus
    .options(...) chains hanging off any of those."""
    if isinstance(dec, ast.Call):
        func = dec.func
        if isinstance(func, ast.Attribute) and func.attr == "options":
            return _is_remote_decorator(func.value)
        return _is_remote_decorator(func)
    dotted = _dotted(dec)
    return dotted is not None and dotted[-1] == "remote"


def _is_serve_deployment_decorator(dec: ast.AST) -> bool:
    """@serve.deployment / @deployment, bare or called, plus
    .options(...) chains (GC009 class detection)."""
    if isinstance(dec, ast.Call):
        func = dec.func
        if isinstance(func, ast.Attribute) and func.attr == "options":
            return _is_serve_deployment_decorator(func.value)
        return _is_serve_deployment_decorator(func)
    dotted = _dotted(dec)
    return dotted is not None and dotted[-1] == "deployment"


def _is_lockish(node: ast.AST, known_locks: Set[str]) -> bool:
    """Heuristic: the receiver of .acquire() looks like a lock."""
    dotted = _dotted(node)
    if dotted is None:
        return False
    name = dotted[-1]
    return "lock" in name.lower() or ".".join(dotted) in known_locks \
        or name in known_locks


def _assigned_names(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_assigned_names(elt))
        return out
    return []


def _iter_own_exprs(stmt: ast.stmt):
    """Expression nodes belonging to this statement only — prunes nested
    statements (handled by the block walk) and function/class bodies
    (handled with their own scope context)."""
    stack: List[ast.AST] = []
    for child in ast.iter_child_nodes(stmt):
        if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
            stack.append(child)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
                stack.append(child)


def _remote_handle_class_info(call: ast.Call
                              ) -> Tuple[Optional[str], Optional[int]]:
    """``Cls.remote(...)`` / ``Cls.options(...).remote(...)`` ->
    (dotted class name as written, max_concurrency literal or None).
    Only CamelCase final components count as classes — ``h.m.remote()``
    is a method submit, not a handle creation. Shared by the local
    GC008 prepass and the engine's fact extractor so the
    receiver->class correlation cannot diverge between the two."""
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr != "remote":
        return None, None
    base = func.value
    max_conc = None
    if isinstance(base, ast.Call) and isinstance(base.func, ast.Attribute) \
            and base.func.attr == "options":
        for kw in base.keywords:
            if kw.arg == "max_concurrency" \
                    and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int):
                max_conc = kw.value.value
        base = base.func.value
    dotted = _dotted(base)
    name = ".".join(dotted) if dotted else None
    if not name or not name.split(".")[-1][:1].isupper():
        return None, None
    return name, max_conc


def _remote_handle_class(call: ast.Call) -> Optional[str]:
    """The GC008 receiver->class correlation: just the class name."""
    return _remote_handle_class_info(call)[0]


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """If `value` is a call to a known-unserializable constructor, name it."""
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    if dotted is None:
        return None
    return _UNSERIALIZABLE_CTORS.get(dotted) \
        or _UNSERIALIZABLE_CTORS.get(dotted[-1:])


# ---------------------------------------------------------------------------
# the checker


class _FileChecker:
    def __init__(self, path: str, source: str, tree: ast.Module,
                 enabled: Set[str]):
        self.path = path
        self.enabled = enabled
        self.findings: List[Finding] = []
        per_line, file_wide = _parse_suppressions(source)
        self._suppress_line = per_line
        self._suppress_file = file_wide
        if _gc007_exempt(path):
            self._suppress_file = set(file_wide) | {"GC007"}
        self.tree = tree
        # module-level unserializable objects: name -> ctor description
        self.module_unserializable: Dict[str, str] = {}
        # names `from ray_tpu import get/wait` was bound to
        self.bare_get_names: Set[str] = set()
        # GC008: methods bound into a compiled graph anywhere in this
        # module (`<expr>.<method>.bind(...)` call sites). Stored as
        # (class_name, method) when the receiver resolves to a known
        # `x = Cls.remote()` / `x = Cls.options(...).remote()` handle —
        # so a same-named method on an UNRELATED actor class is not
        # flagged — and ("", method) when the receiver is dynamic (loop
        # var, container element): conservative module-wide match.
        handle_cls: Dict[str, str] = {}
        bind_calls: List[ast.Call] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                cls = _remote_handle_class(node.value)
                if cls:
                    for t in node.targets:
                        for nm in _assigned_names(t):
                            handle_cls[nm] = cls
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "bind" \
                    and isinstance(node.func.value, ast.Attribute):
                bind_calls.append(node)
        self.cgraph_bound: Set[Tuple[str, str]] = set()
        for node in bind_calls:
            recv = node.func.value.value
            cls = (handle_cls.get(recv.id, "")
                   if isinstance(recv, ast.Name) else "")
            self.cgraph_bound.add((cls, node.func.value.attr))
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                kind = _ctor_kind(stmt.value)
                if kind:
                    for t in stmt.targets:
                        for name in _assigned_names(t):
                            self.module_unserializable[name] = kind
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                kind = _ctor_kind(stmt.value)
                if kind and isinstance(stmt.target, ast.Name):
                    self.module_unserializable[stmt.target.id] = kind
            elif isinstance(stmt, ast.ImportFrom) and stmt.module \
                    and stmt.module.split(".")[0] in ("ray_tpu", "ray"):
                for alias in stmt.names:
                    if alias.name == "get":
                        self.bare_get_names.add(alias.asname or alias.name)

    # -- reporting --------------------------------------------------------

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        if rule not in self.enabled or rule in self._suppress_file:
            return
        line = getattr(node, "lineno", 0)
        sup = self._suppress_line.get(line, ())
        if rule in sup:
            return
        self.findings.append(Finding(
            path=self.path, line=line,
            col=getattr(node, "col_offset", 0) + 1, rule=rule,
            message=message))

    # -- entry ------------------------------------------------------------

    def run(self) -> List[Finding]:
        self._walk_block(self.tree.body, remote=False, is_async=False,
                         fn=None)
        return self.findings

    # -- recursive walk with scope context --------------------------------

    def _walk_block(self, stmts: Sequence[ast.stmt], remote: bool,
                    is_async: bool, fn: Optional[dict],
                    actor_class: bool = False,
                    cgraph: bool = False,
                    class_name: str = "",
                    serve_async: bool = False,
                    serve_class: bool = False) -> None:
        for idx, stmt in enumerate(stmts):
            self._walk_stmt(stmt, stmts, idx, remote, is_async, fn,
                            actor_class, cgraph, class_name, serve_async,
                            serve_class)

    def _walk_stmt(self, stmt: ast.stmt, siblings: Sequence[ast.stmt],
                   idx: int, remote: bool, is_async: bool,
                   fn: Optional[dict], actor_class: bool,
                   cgraph: bool = False, class_name: str = "",
                   serve_async: bool = False,
                   serve_class: bool = False) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_remote = remote or actor_class \
                or any(_is_remote_decorator(d) for d in stmt.decorator_list)
            fn_async = isinstance(stmt, ast.AsyncFunctionDef)
            # GC008 context: an actor method bound into a compiled graph
            # somewhere in this module — matched by (class, method) when
            # the bind receiver resolved to a handle of THIS class, or
            # by bare method name for dynamic receivers (nested defs
            # inherit the context)
            fn_cgraph = cgraph or (actor_class and (
                (class_name, stmt.name) in self.cgraph_bound
                or ("", stmt.name) in self.cgraph_bound))
            # GC009 context: an async method of a serve deployment class
            # (nested defs inherit it — a sync helper called inline from
            # the async method still blocks the replica's event loop)
            fn_serve_async = serve_async or (serve_class and fn_async)
            ctx = self._fn_context(stmt)
            self._walk_block(stmt.body, remote=fn_remote, is_async=fn_async,
                             fn=ctx, cgraph=fn_cgraph,
                             serve_async=fn_serve_async)
            return
        if isinstance(stmt, ast.ClassDef):
            cls_remote = any(_is_remote_decorator(d)
                             for d in stmt.decorator_list)
            cls_serve = any(_is_serve_deployment_decorator(d)
                            for d in stmt.decorator_list)
            self._walk_block(stmt.body, remote=remote, is_async=is_async,
                             fn=fn, actor_class=cls_remote or actor_class,
                             cgraph=cgraph, class_name=stmt.name,
                             serve_async=serve_async,
                             serve_class=cls_serve or serve_class)
            return
        if isinstance(stmt, ast.Global) and remote and fn is not None:
            mutated = [n for n in stmt.names if n in fn["stores"]]
            if mutated:
                self.report(
                    "GC003", stmt,
                    f"task body mutates module global(s) "
                    f"{', '.join(sorted(mutated))}; the write happens in the "
                    f"worker process and is lost — return the value or use "
                    f"an actor")
        if isinstance(stmt, ast.Try):
            self._check_gc005(stmt)
        if isinstance(stmt, ast.While):
            self._check_gc012(stmt)
        # GC006 on statement-position acquire() calls
        self._check_gc006(stmt, siblings, idx)
        # this statement's own expressions: GC001/GC002/GC004/GC008/GC009
        for node in _iter_own_exprs(stmt):
            self._check_expr(node, remote, is_async, fn, cgraph,
                             serve_async)
        # recurse into child statement blocks (for/while/if/with/try bodies)
        for field_name in ("body", "orelse", "finalbody"):
            child = getattr(stmt, field_name, None)
            if isinstance(child, list) and child \
                    and isinstance(child[0], ast.stmt):
                self._walk_block(child, remote, is_async, fn, actor_class,
                                 cgraph, class_name, serve_async,
                                 serve_class)
        for handler in getattr(stmt, "handlers", ()):
            self._walk_block(handler.body, remote, is_async, fn,
                             actor_class, cgraph, class_name, serve_async,
                             serve_class)
        for case in getattr(stmt, "cases", ()):
            self._walk_block(case.body, remote, is_async, fn, actor_class,
                             cgraph, class_name, serve_async, serve_class)

    def _fn_context(self, fndef) -> dict:
        """Names a function binds locally (params + assignments) and
        names it stores to (for GC003)."""
        locals_: Set[str] = set()
        stores: Set[str] = set()
        declared_global: Set[str] = set()
        args = fndef.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            locals_.add(a.arg)
        for node in ast.walk(fndef):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                locals_.add(node.id)
                stores.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fndef:
                locals_.add(node.name)
            elif isinstance(node, ast.Global):
                # declared-global names resolve to the module, not locals
                declared_global.update(node.names)
        return {"locals": locals_ - declared_global, "stores": stores}

    # -- expression-level rules -------------------------------------------

    def _check_expr(self, node: ast.AST, remote: bool, is_async: bool,
                    fn: Optional[dict], cgraph: bool = False,
                    serve_async: bool = False) -> None:
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and node.func.id == "print":
                self.report(
                    "GC007", node,
                    "bare print() in library code is un-attributed and "
                    "un-queryable; use ray_tpu.util.logs.get_logger() so "
                    "the line reaches the cluster log store with task "
                    "attribution (suppress where print IS the surface)")
            if remote:
                self._check_gc001(node)
            if cgraph:
                self._check_gc008(node)
            if serve_async:
                self._check_gc009(node)
            if is_async:
                dotted = _dotted(node.func)
                if dotted == ("time", "sleep"):
                    self.report(
                        "GC004", node,
                        "time.sleep() in an async function blocks the "
                        "actor's event loop for every queued request; use "
                        "`await asyncio.sleep(...)`")
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and remote and fn is not None:
            kind = self.module_unserializable.get(node.id)
            if kind and node.id not in fn["locals"]:
                self.report(
                    "GC002", node,
                    f"remote closure captures module-level {kind} "
                    f"'{node.id}' which cannot be serialized to a worker; "
                    f"create it inside the task or hold it in an actor")

    def _is_blocking_get(self, call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "get":
            recv = func.value
            dotted = _dotted(recv)
            if dotted in (("ray_tpu",), ("ray",)):
                return True  # ray_tpu.get(...) inside a task
            if isinstance(recv, ast.Call):
                inner = _dotted(recv.func)
                if inner is not None and inner[-1] in ("get_runtime",):
                    return True  # get_runtime().get(...)
                if isinstance(recv.func, ast.Attribute) \
                        and recv.func.attr == "remote":
                    return True  # f.remote(...).get()
        elif isinstance(func, ast.Name) and func.id in self.bare_get_names:
            return True  # `from ray_tpu import get` then get(...)
        return False

    def _check_gc001(self, call: ast.Call) -> None:
        if self._is_blocking_get(call):
            self.report(
                "GC001", call,
                "blocking get() inside a @remote function/actor method can "
                "deadlock when the worker pool is saturated (the waiting "
                "task holds the lease its child needs); restructure with "
                "ref-passing, or suppress if the nesting depth is bounded")

    def _check_gc008(self, call: ast.Call) -> None:
        """Inside a method bound into a compiled graph: dynamic task
        submission (`.remote(...)`) and blocking gets defeat the static
        contract — the resident loop has no scheduler behind it."""
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "remote":
            self.report(
                "GC008", call,
                "dynamic .remote() submission inside a method bound into "
                "a compiled graph reintroduces per-call scheduling and "
                "can deadlock against the resident loop; keep bound "
                "methods pure compute and do dynamic work outside the "
                "graph")
            return
        if self._is_blocking_get(call):
            self.report(
                "GC008", call,
                "blocking get() inside a method bound into a compiled "
                "graph stalls the resident loop (and every downstream "
                "stage) on the dynamic task plane; pass the value "
                "through the graph's channels instead")

    def _check_gc009(self, call: ast.Call) -> None:
        """Inside an async serve-deployment method: a blocking get() or
        a synchronous `<handle>.remote(...).result()` pins the replica's
        event loop — every queued request on this replica stalls behind
        it."""
        if self._is_blocking_get(call):
            self.report(
                "GC009", call,
                "blocking get() inside an async serve deployment method "
                "stalls the replica's event loop for every queued "
                "request; await the response (or run the blocking call "
                "in an executor)")
            return
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "result" \
                and isinstance(func.value, ast.Call) \
                and isinstance(func.value.func, ast.Attribute) \
                and func.value.func.attr == "remote":
            self.report(
                "GC009", call,
                "synchronous handle call (.remote(...).result()) inside "
                "an async serve deployment method blocks the event loop "
                "until the downstream deployment answers; await the "
                "DeploymentResponse instead")

    # -- statement-level rules --------------------------------------------

    # names whose presence in a retry loop signals an explicit bound
    _GC012_BOUND_NAMES = ("deadline", "attempt", "retries", "backoff",
                          "budget")
    # calls that make the loop policy-governed (util/retry.py)
    _GC012_POLICY_CALLS = ("sleeps", "call_with_retry", "backoff")

    def _check_gc012(self, loop: ast.While) -> None:
        """Unbounded bare retry loop: ``while True`` + a try whose
        handler swallows-and-continues around a remote/connect call,
        with no deadline comparison, growing backoff, attempt counter,
        or util.retry usage anywhere in the loop."""
        if not (isinstance(loop.test, ast.Constant) and loop.test.value):
            return
        retry_site = None
        for node in ast.walk(loop):
            if not isinstance(node, ast.Try):
                continue
            if not any(self._gc012_retryable_call(c)
                       for s in node.body for c in ast.walk(s)):
                continue
            for handler in node.handlers:
                if self._gc012_handler_swallows(handler):
                    retry_site = node
                    break
            if retry_site is not None:
                break
        if retry_site is None:
            return
        if self._gc012_loop_is_bounded(loop):
            return
        self.report(
            "GC012", retry_site,
            "unbounded bare retry loop: the handler swallows the error "
            "and retries the remote call/connect forever with no "
            "backoff, deadline, or attempt budget — hammers a dead peer "
            "and hides the fault; use ray_tpu.util.retry (RetryPolicy."
            "sleeps / call_with_retry) or add an explicit bound")

    @staticmethod
    def _gc012_retryable_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in ("remote", "connect",
                                  "create_connection"):
            return True
        return isinstance(func, ast.Name) and func.id in (
            "connect", "create_connection")

    @staticmethod
    def _gc012_handler_swallows(handler: ast.ExceptHandler) -> bool:
        """Swallow-and-retry shape: the handler neither re-raises nor
        leaves the loop (no raise/return/break anywhere in it)."""
        for n in ast.walk(handler):
            if isinstance(n, (ast.Raise, ast.Return, ast.Break)):
                return False
        return True

    def _gc012_loop_is_bounded(self, loop: ast.While) -> bool:
        for n in ast.walk(loop):
            if isinstance(n, ast.Compare):
                # a deadline/attempt comparison anywhere bounds the loop
                for side in [n.left] + list(n.comparators):
                    for leaf in ast.walk(side):
                        if isinstance(leaf, ast.Name) and any(
                                b in leaf.id.lower()
                                for b in self._GC012_BOUND_NAMES):
                            return True
                        if isinstance(leaf, ast.Call):
                            d = _dotted(leaf.func)
                            if d and d[-1] in ("monotonic", "time",
                                               "perf_counter"):
                                return True
            elif isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d is None:
                    continue
                if d[-1] in self._GC012_POLICY_CALLS:
                    return True
                if d[-1] == "sleep" and n.args and not isinstance(
                        n.args[0], ast.Constant):
                    return True  # variable sleep = growing backoff
            elif isinstance(n, ast.Name) and any(
                    b in n.id.lower() for b in self._GC012_BOUND_NAMES):
                return True
        return False

    def _check_gc005(self, node: ast.Try) -> None:
        for handler in node.handlers:
            if handler.type is not None:
                continue
            reraises = any(isinstance(n, ast.Raise) and n.exc is None
                           for n in ast.walk(handler))
            if not reraises:
                self.report(
                    "GC005", handler,
                    "bare `except:` without re-raise swallows TaskError/"
                    "ActorDiedError (and SystemExit/KeyboardInterrupt), "
                    "hiding worker death; catch Exception or specific "
                    "framework errors instead")

    def _check_gc006(self, stmt: ast.stmt, siblings: Sequence[ast.stmt],
                     idx: int) -> None:
        call = None
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
        elif isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Call):
            call = stmt.value
        if call is None or not isinstance(call.func, ast.Attribute) \
                or call.func.attr != "acquire":
            return
        recv = call.func.value
        known = set(self.module_unserializable)
        if not _is_lockish(recv, known):
            return
        recv_dump = ast.dump(recv)
        # pattern A: lock.acquire() immediately followed by
        # try: ... finally: lock.release()
        nxt = siblings[idx + 1] if idx + 1 < len(siblings) else None
        if isinstance(nxt, ast.Try) \
                and self._releases(nxt.finalbody, recv_dump):
            return
        # pattern A': timed acquire — `got = lock.acquire(timeout=...)`
        # guarded by `if got:` wrapping a try/finally release
        if isinstance(stmt, ast.Assign) and isinstance(nxt, ast.If):
            for n in ast.walk(nxt):
                if isinstance(n, ast.Try) \
                        and self._releases(n.finalbody, recv_dump):
                    return
        # pattern B: the acquire sits inside a try whose finally releases
        # (acquire-inside-try is its own subtle bug, but the lock does get
        # released; GC006 targets the leak)
        if self._enclosing_try_releases(stmt, recv_dump):
            return
        self.report(
            "GC006", stmt,
            "lock acquired without `with` or try/finally: an exception "
            "between acquire() and release() leaks the lock and wedges "
            "every later acquirer; use `with lock:` (preferred) or "
            "acquire();try/finally:release()")

    def _releases(self, stmts: Sequence[ast.stmt], recv_dump: str) -> bool:
        for s in stmts:
            for n in ast.walk(s):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "release" \
                        and ast.dump(n.func.value) == recv_dump:
                    return True
        return False

    def _enclosing_try_releases(self, stmt: ast.stmt,
                                recv_dump: str) -> bool:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Try):
                in_body = any(stmt is s or any(stmt is d for d in ast.walk(s))
                              for s in node.body)
                if in_body and self._releases(node.finalbody, recv_dump):
                    return True
        return False


# ---------------------------------------------------------------------------
# driver


def check_source(source: str, path: str = "<string>",
                 rules: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one source blob; parse errors raise SyntaxError."""
    tree = ast.parse(source, filename=path)
    checker = _FileChecker(path, source, tree, rules or set(RULES))
    return checker.run()


def check_file(path: str,
               rules: Optional[Set[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return check_source(f.read(), path, rules)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # _graftcheck_fixtures holds intentionally-buggy test
                # inputs; they are linted by passing the path explicitly,
                # never by tree discovery (lint.sh must stay green)
                dirs[:] = [d for d in dirs
                           if not d.startswith(".") and d != "__pycache__"
                           and d != "_graftcheck_fixtures"]
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        else:
            raise FileNotFoundError(p)
    return out


# Local rules only — the engine runs these per file (cache-keyed by
# content hash) and layers the whole-program rules on top.
LOCAL_RULES: Set[str] = {"GC001", "GC002", "GC003", "GC004", "GC005",
                         "GC006", "GC007", "GC008", "GC009", "GC012"}
