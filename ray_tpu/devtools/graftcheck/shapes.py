"""Shape-and-spec abstract domain for graftcheck v4.

The vocabulary layer under :mod:`.rules_shapes`: abstract values, the
constant-expression evaluators that resolve them, the codec-call
classifier GC043 keys off, and the contraction-structure extractors
GC041 consumes. Everything here is pure — no CFG, no project index —
so the rules module stays a thin orchestration layer, the same split
:mod:`.rules_lifecycle` uses over :mod:`.cfg`/:mod:`.dataflow`.

Abstract values are per-name fact sets, each fact a hashable tuple:

``("shape", dims)``
    The name is an array of statically-known shape; every dim is an
    ``int`` or ``None``. A *must* fact — joins intersect it away when
    the branches disagree.

``("sm", lineno)``
    The name is the callable returned by the ``shard_map``/
    ``lower_shard_map``/``lower_jit`` site at that line; a later call
    through it attaches the invocation's argument shapes to the site.
    Must fact.

``("quant", lineno)``
    The value still carries the packed quantized wire encoding
    produced at that line (``quantize``/``quantize_blocks``), and no
    decode has run on this path. A *may* fact — joins union it, since
    a reduce over a possibly-still-quantized payload is the bug.

``("donated", lineno)``
    The buffer was passed at a ``donate_argnums`` position of a jitted
    call at that line and not rebound since; any read is a
    use-after-donation (GC022). May fact.

Resolution is deliberately shallow and sound-when-it-fires: literal
tuples, module int/tuple constants, single-assignment locals, and
``+ - * // %`` over them. Anything else evaluates to unknown and the
rules stay silent — the contract every GC0xx rule keeps.
"""
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Tuple

# -- fact-set algebra --------------------------------------------------------

MAY_TAGS = ("quant", "donated")

Facts = frozenset
EMPTY: Facts = frozenset()


def join_facts(a: Facts, b: Facts) -> Facts:
    """Union of may facts, intersection of must facts."""
    out = set(a & b)
    for f in (a | b) - (a & b):
        if f[0] in MAY_TAGS:
            out.add(f)
    return frozenset(out)


def join_env(a: Dict[str, Facts], b: Dict[str, Facts]) -> Dict[str, Facts]:
    out: Dict[str, Facts] = {}
    for name in set(a) | set(b):
        f = join_facts(a.get(name, EMPTY), b.get(name, EMPTY))
        if f:
            out[name] = f
    return out


def shape_of(facts: Facts) -> Optional[Tuple[Optional[int], ...]]:
    for f in facts:
        if f[0] == "shape":
            return f[1]
    return None


def quant_line(facts: Facts) -> Optional[int]:
    for f in facts:
        if f[0] == "quant":
            return f[1]
    return None


def donated_line(facts: Facts) -> Optional[int]:
    for f in facts:
        if f[0] == "donated":
            return f[1]
    return None


def sm_site(facts: Facts) -> Optional[int]:
    for f in facts:
        if f[0] == "sm":
            return f[1]
    return None


# -- constant evaluation -----------------------------------------------------


class ConstEnv:
    """Int/tuple constants visible to one function: module-level consts
    from the summary plus single-assignment locals (flow-insensitive —
    a name assigned twice is dropped)."""

    def __init__(self, summary: Dict[str, Any]):
        self.ints: Dict[str, int] = dict(summary.get("int_consts", {}))
        self.tuples: Dict[str, Tuple[Optional[int], ...]] = {
            k: tuple(v)
            for k, v in summary.get("int_tuple_consts", {}).items()}

    def add_locals(self, stmts) -> None:
        seen: Dict[str, int] = {}
        pending: List[Tuple[str, ast.AST]] = []
        for st in stmts:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                nm = st.targets[0].id
                seen[nm] = seen.get(nm, 0) + 1
                pending.append((nm, st.value))
        for nm, value in pending:
            if seen[nm] != 1:
                self.ints.pop(nm, None)
                self.tuples.pop(nm, None)
                continue
            v = eval_int(value, self)
            if v is not None:
                self.ints[nm] = v
                continue
            t = eval_shape(value, self)
            if t is not None:
                self.tuples[nm] = t


def eval_int(expr: Optional[ast.AST], env: ConstEnv) -> Optional[int]:
    if expr is None:
        return None
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, bool) or not isinstance(expr.value, int):
            return None
        return expr.value
    if isinstance(expr, ast.Name):
        return env.ints.get(expr.id)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = eval_int(expr.operand, env)
        return -v if v is not None else None
    if isinstance(expr, ast.BinOp):
        left = eval_int(expr.left, env)
        right = eval_int(expr.right, env)
        if left is None or right is None:
            return None
        if isinstance(expr.op, ast.Add):
            return left + right
        if isinstance(expr.op, ast.Sub):
            return left - right
        if isinstance(expr.op, ast.Mult):
            return left * right
        if isinstance(expr.op, ast.FloorDiv) and right != 0:
            return left // right
        if isinstance(expr.op, ast.Mod) and right != 0:
            return left % right
    return None


def eval_dim(expr: Optional[ast.AST], env: ConstEnv) -> Any:
    """One shape dim: an int, a ``("sym", dotted)`` record for a name
    this module can't resolve (the project pass resolves it through
    ``lookup_int_const`` — model-config constants live cross-file), or
    None."""
    v = eval_int(expr, env)
    if v is not None:
        return v
    if isinstance(expr, ast.Name):
        return ("sym", expr.id)
    if isinstance(expr, ast.Attribute):
        parts: List[str] = [expr.attr]
        cur = expr.value
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            return ("sym", ".".join(reversed(parts)))
    return None


def dim_value(d: Any, lookup_int) -> Optional[int]:
    """A recorded shape dim -> concrete int: ints pass through,
    ``("sym", name)`` records resolve through `lookup_int` (JSON
    round-trips the tuple to a list); anything else is unknown."""
    if isinstance(d, bool):
        return None
    if isinstance(d, int):
        return d
    if isinstance(d, (list, tuple)) and len(d) == 2 and d[0] == "sym":
        return lookup_int(d[1])
    return None


def eval_shape(expr: Optional[ast.AST], env: ConstEnv
               ) -> Optional[Tuple[Any, ...]]:
    """A shape tuple with every dim an int, a ``("sym", name)`` record,
    or None (unknown dim); None when the expression is not a shape at
    all."""
    if expr is None:
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        return tuple(eval_dim(e, env) for e in expr.elts)
    if isinstance(expr, ast.Name):
        return env.tuples.get(expr.id)
    v = eval_int(expr, env)   # scalar: 1-tuple only when concrete
    return (v,) if v is not None else None


# -- array-producing calls ---------------------------------------------------

_ARRAY_CTORS_SHAPE0 = {"zeros", "ones", "empty", "full"}
_ARRAY_CTORS_SHAPE1 = {"normal", "uniform", "randint", "bernoulli",
                       "broadcast_to"}


def shape_from_call(call: ast.Call, env: ConstEnv
                    ) -> Optional[Tuple[Optional[int], ...]]:
    """``jnp.zeros((4, 8))``-family shapes, ``x.reshape(a, b)``,
    ``jnp.arange(n)``; None for anything else."""
    d = _dotted_last(call.func)
    if d is None:
        return None
    shape_expr: Optional[ast.AST] = None
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    if d in _ARRAY_CTORS_SHAPE0:
        shape_expr = kw.get("shape") or (call.args[0] if call.args else None)
    elif d in _ARRAY_CTORS_SHAPE1:
        shape_expr = kw.get("shape") or (call.args[1] if len(call.args) > 1
                                         else None)
    elif d == "arange":
        n = eval_int(call.args[0], env) if call.args else None
        return (n,) if n is not None else None
    elif d == "reshape" and isinstance(call.func, ast.Attribute):
        if len(call.args) == 1:
            return eval_shape(call.args[0], env)
        return tuple(eval_dim(a, env) for a in call.args) or None
    if shape_expr is None:
        return None
    return eval_shape(shape_expr, env)


def _dotted_last(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


# -- codec classification (GC043) --------------------------------------------

ENCODE_OPS = {"quantize", "quantize_blocks"}
DECODE_OPS = {"dequantize", "dequantize_blocks"}
# ops that move a payload without interpreting it: quantization survives
WIRE_OPS = {"all_to_all", "ppermute", "all_gather", "pshuffle", "pcast"}
# ops that arithmetically combine payloads: quantization must not survive
REDUCE_OPS = {"psum", "pmean", "pmax", "pmin", "psum_scatter"}
_NUMPY_REDUCE = {"sum", "mean", "add"}
# host-plane point-to-point sends: the decode obligation moves to the
# receive leg, checked module-wide
SEND_OPS = {"send", "put", "push", "isend"}


def classify_codec(call: ast.Call) -> Optional[str]:
    """-> 'encode' | 'decode' | 'wire' | 'reduce' | 'send' | None.
    The same single classifier extension point GC030's lifecycle
    vocabulary uses — new codec families plug in here."""
    d = _dotted_last(call.func)
    if d is None:
        return None
    if d in ENCODE_OPS:
        return "encode"
    if d in DECODE_OPS:
        return "decode"
    if d == "astype":
        # manual-decode idiom: widening back to a float dtype clears
        # the packed-encoding flag
        return "decode"
    if d in WIRE_OPS:
        return "wire"
    if d in REDUCE_OPS:
        return "reduce"
    if d in _NUMPY_REDUCE and isinstance(call.func, ast.Attribute):
        base = call.func.value
        bd = _dotted_last(base) if isinstance(base, (ast.Name, ast.Attribute)) \
            else None
        if bd in ("jnp", "np", "numpy", "lax"):
            return "reduce"
    if d in SEND_OPS and isinstance(call.func, ast.Attribute):
        return "send"
    return None


# -- contraction structure (GC041) -------------------------------------------


def parse_einsum_subscripts(spec: str) -> Optional[List[List[int]]]:
    """Per-operand contraction-dim positions of an explicit einsum
    subscript string; None when it cannot be parsed soundly."""
    spec = spec.replace(" ", "")
    if "..." in spec or "->" not in spec:
        return None
    lhs, rhs = spec.split("->", 1)
    operands = lhs.split(",")
    contracted = {c for op in operands for c in op} - set(rhs)
    return [[i for i, c in enumerate(op) if c in contracted]
            for op in operands]


def contraction_records(fndef: ast.AST, params: Sequence[str],
                        walk_expr) -> List[Dict[str, Any]]:
    """Contractions in the function's own scope whose operands are
    direct parameters: ``[{"kind", "lineno", "operands":
    [{"param": idx, "dims": [pos, ...]}, ...]}]``. ``dims`` entries may
    be negative (counted from the end) for matmul-family ops."""
    out: List[Dict[str, Any]] = []
    pidx = {p: i for i, p in enumerate(params)}

    def param_of(node: ast.AST) -> Optional[int]:
        return pidx.get(node.id) if isinstance(node, ast.Name) else None

    def add(kind: str, lineno: int, ops: List[Tuple[Optional[int],
                                                    List[int]]]) -> None:
        operands = [{"param": p, "dims": dims} for p, dims in ops
                    if p is not None and dims]
        if operands:
            out.append({"kind": kind, "lineno": lineno,
                        "operands": operands})

    for node in walk_expr(fndef):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
            add("matmul", node.lineno,
                [(param_of(node.left), [-1]), (param_of(node.right), [-2])])
            continue
        if not isinstance(node, ast.Call):
            continue
        d = _dotted_last(node.func)
        if d == "einsum" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            per_op = parse_einsum_subscripts(node.args[0].value)
            if per_op is None:
                continue
            ops = []
            for k, dims in enumerate(per_op):
                arg = node.args[1 + k] if 1 + k < len(node.args) else None
                ops.append((param_of(arg) if arg is not None else None,
                            dims))
            add("einsum", node.lineno, ops)
        elif d in ("matmul", "dot"):
            if len(node.args) >= 2:
                add(d, node.lineno, [(param_of(node.args[0]), [-1]),
                                     (param_of(node.args[1]), [-2])])
        elif d == "dot_general" and len(node.args) >= 3:
            dn = node.args[2]
            parsed = _parse_dimension_numbers(dn)
            if parsed is not None:
                (ca, cb) = parsed
                add("dot_general", node.lineno,
                    [(param_of(node.args[0]), ca),
                     (param_of(node.args[1]), cb)])
    return out


def _parse_dimension_numbers(dn: ast.AST
                             ) -> Optional[Tuple[List[int], List[int]]]:
    """Literal ``((contract_a, contract_b), (batch_a, batch_b))`` ->
    (contract_a, contract_b)."""
    if not isinstance(dn, ast.Tuple) or not dn.elts:
        return None
    contract = dn.elts[0]
    if not isinstance(contract, ast.Tuple) or len(contract.elts) != 2:
        return None

    def ints(node: ast.AST) -> Optional[List[int]]:
        if not isinstance(node, (ast.Tuple, ast.List)):
            return None
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            vals.append(e.value)
        return vals

    ca = ints(contract.elts[0])
    cb = ints(contract.elts[1])
    if ca is None or cb is None:
        return None
    return ca, cb


# -- spec-record resolution (GC040/041/044) ----------------------------------


def resolve_p_entries(record: Dict[str, Any], lookup_str
                      ) -> Optional[List[Optional[List[str]]]]:
    """A ``{"kind": "p"}`` spec record -> per-dim mesh-axis-name lists
    (``[]`` = replicated, ``None`` = that dim is unresolvable).
    `lookup_str` resolves a symbol to a module string constant."""
    if record.get("kind") != "p":
        return None
    out: List[Optional[List[str]]] = []
    for e in record["entries"]:
        if e is None:
            out.append([])
        elif "lit" in e:
            out.append([e["lit"]])
        elif "sym" in e:
            const = lookup_str(e["sym"])
            out.append([const] if const is not None else None)
        elif "tup" in e:
            axes: Optional[List[str]] = []
            for sub in e["tup"]:
                if sub is not None and "lit" in sub:
                    axes.append(sub["lit"])
                elif sub is not None and "sym" in sub:
                    const = lookup_str(sub["sym"])
                    if const is None:
                        axes = None
                        break
                    axes.append(const)
                else:
                    axes = None
                    break
            out.append(axes)
        else:
            out.append(None)
    return out


def logical_entry_axes(logical: Optional[str],
                       table: Optional[Dict[str, Any]]
                       ) -> Optional[List[str]]:
    """A logical dim name -> the mesh-axis-role list its layout table
    maps it to (``[]`` = replicated / contraction-safe); None unknown."""
    if logical is None:
        return []
    if table is None or logical not in table:
        return None
    axes = table[logical]
    if axes is None:
        return []
    if isinstance(axes, str):
        return [axes]
    if isinstance(axes, (list, tuple)) \
            and all(isinstance(a, str) for a in axes):
        return list(axes)
    return None
