"""graftcheck — framework-aware static analysis for ray_tpu code.

Two layers (docs/GRAFTCHECK.md has the full rule tables):

- **Per-file rules** (:mod:`.local`): GC001-GC008, decidable from one
  module alone — blocking get() in remote bodies, unserializable
  closure capture, worker-side global mutation, event-loop sleeps,
  swallowed framework errors, leak-prone lock handling, bare print()
  in library code, dynamic calls in compiled-graph-bound methods.

- **Whole-program engine** (:mod:`.engine`): builds a symbol table over
  every file, resolves imports (including package re-export chains),
  and constructs the *remote call graph* — which functions are
  ``@remote`` tasks / actor methods, which call sites submit to which,
  and where blocking ``get()`` waits occur — with a content-hash file
  cache so repeat runs only re-parse changed files. On top of it run
  GC010 (actor-deadlock wait cycles), GC011 (interprocedural
  serialization flow), one-level interprocedural upgrades of
  GC001/GC003, call-graph-resolved GC008 binding, and the GC020 SPMD
  series (unbound collective axes, in_specs arity, donated-buffer
  reuse) — see :mod:`.rules_project` / :mod:`.rules_spmd`.

- **Path-sensitive dataflow layer** (v3): :mod:`.cfg` builds
  per-function control-flow graphs (exception edges, ``finally``
  duplication, ``with`` as acquire + guaranteed release, branch
  assumes), :mod:`.dataflow` runs a generic forward
  abstract-interpretation fixpoint over them, and
  :mod:`.rules_lifecycle` polices the framework's paired-lifecycle
  invariants with GC030-GC033 (leaks, double-release, except-swallowed
  release, conditional-acquire/unconditional-release) — including
  interprocedural ownership summaries resolved through the import
  graph. The pass runs at parse time; its findings and pending facts
  ride the file cache.

- **Shape-and-spec abstract interpretation** (v4): :mod:`.shapes`
  defines a fact-set domain (concrete and *symbolic* array shapes,
  quantized-payload and donated-buffer provenance) that
  :mod:`.rules_shapes` runs over the same CFG/fixpoint engine, with
  cross-file resolution of model-config constants, mesh axis sizes,
  and logical-layout spec tables through the project index. On top:
  GC040 (mesh-axis divisibility of shard_map inputs), GC041 (sharded
  contraction dims in matmul/dot_general/einsum), GC042 (Pallas
  BlockSpec consistency), GC043 (wire-codec encode/decode pairing),
  GC044 (collective geometry), and a path-sensitive GC022 (donated
  reads only fire on paths through the donating call). Shape facts
  ride the file cache; ``--diff REF`` scopes reporting to changed
  files plus their reverse-dependency closure.

``check_source`` / ``check_file`` compose both layers for a single
blob (the whole-program passes then see exactly one module);
``check_project`` runs the full engine; ``main`` is the CLI
(``python -m ray_tpu.devtools.graftcheck``, with ``--sarif``,
``--baseline``, caching flags, and the ``graph`` DOT subcommand).
"""
from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from .local import (LOCAL_RULES, RULES, Finding, _FileChecker,
                    iter_python_files)
from .engine import (ProjectIndex, ProjectResult, build_call_graph,
                     check_project, to_dot)
from .summary import extract
from . import rules_lifecycle, rules_project, rules_shapes, rules_spmd
from .cli import main

__all__ = [
    "RULES", "LOCAL_RULES", "Finding",
    "check_source", "check_file", "check_project", "iter_python_files",
    "ProjectIndex", "ProjectResult", "build_call_graph", "to_dot",
    "main",
]


def check_source(source: str, path: str = "<string>",
                 rules: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one source blob with both layers; the whole-program rules
    see a single-module project (GC008 keeps the module-local
    heuristic here — engine-resolved binding needs ``check_project``).
    Parse errors raise SyntaxError."""
    enabled = set(rules) if rules is not None else set(RULES)
    tree = ast.parse(source, filename=path)
    findings = _FileChecker(path, source, tree, enabled).run()
    module = os.path.splitext(os.path.basename(path))[0] or "<string>"
    summary, extra = extract(path, source, tree, module)
    findings.extend(f for f in extra if f.rule in enabled)
    findings.extend(f for f in rules_lifecycle.analyze_module(tree, summary)
                    if f.rule in enabled)
    findings.extend(f for f in rules_shapes.analyze_module(tree, summary)
                    if f.rule in enabled)
    index = ProjectIndex([summary])
    graph = build_call_graph(index)
    # GC008 already ran module-locally above; don't double-report
    findings.extend(rules_project.run(index, graph, enabled - {"GC008"}))
    findings.extend(rules_spmd.run(index, enabled))
    findings.extend(rules_shapes.run(index, enabled))
    findings.extend(rules_lifecycle.resolve_pending(index, enabled))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def check_file(path: str,
               rules: Optional[Set[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return check_source(f.read(), path, rules)
