"""GC030-GC033 — CFG-based path-sensitive resource-lifecycle analysis.

The rule family that polices the paired-lifecycle invariants the
framework actually lives by: BlockPool ``alloc``/``retain``/``free``,
store/agent ``allocate_channel``/``release_channel``, collective-group
``create``/``destroy``, raw ``lock.acquire``/``release``, and
``open()``/sockets outside ``with``. A forward abstract interpretation
(:mod:`.dataflow`) over the per-function CFG (:mod:`.cfg`) tracks each
acquired resource's state along every path:

====== =================================================================
GC030  resource leak — an acquired resource reaches a normal function
       exit unreleased on some path (early return, fall-through, a
       swallowing ``except`` that rejoined the flow), is re-acquired in
       a loop while the previous acquisition is still held, is orphaned
       by rebinding its only name, or its allocation result is
       discarded outright
GC031  double-release / use-after-release along any path (the diamond:
       a conditional release followed by an unconditional one; a retain
       after every incoming path released), incl. a manual release
       inside a ``with`` block that releases again on exit
GC032  release skipped by a swallowing ``except``: the release exists
       on the normal path, but an exception raised *before* it lands in
       a handler that neither re-raises nor releases — the path rejoins
       the normal flow with the resource still held. (A swallow around
       *only* the release itself — best-effort close — stays clean.)
GC033  conditional acquire with unconditional release: the release is
       reached on paths where the acquire never ran (release of an
       unheld lock raises; a pool double-accounting hazard). The
       mirrored shape (unconditional acquire, conditional release) is a
       GC030 leak on the skipping path.
====== =================================================================

Interprocedural ownership (riding the v2 engine's call-graph
machinery):

- a function that **returns** the resource or **stores it on self** /
  into a container transfers ownership — no leak is reported in it;
- a *local* helper that releases its parameter counts as a release at
  the call site (module-level fixpoint, so helper chains resolve);
- passing the resource to an **unresolvable** callee is treated as an
  ownership transfer (silent) but recorded as a *pending* finding; the
  project pass (:func:`resolve_pending`) resolves the callee through
  the import graph — a cross-module helper that provably neither
  releases nor takes ownership confirms the leak, one that releases
  confirms a double-release, anything unresolvable stays silent.

Per-function ownership summaries (``releases``/``owns`` param indices)
are exported into the cached file summaries so cross-file resolution
works against cached entries. Generator functions are skipped (a
suspended frame holds resources across a caller-driven schedule) and
counted in the ``--stats`` surface, as are functions past the CFG node
budget.
"""
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import dataflow
from .cfg import (ENTRY, EXCEPT_DISPATCH, EXCEPT_ENTRY, EXIT, FOR_BIND,
                  RAISE_EXIT, STMT, TEST, WITH_ENTER, WITH_EXIT,
                  CFGTooLarge, build_cfg, handler_swallows, is_generator)
from .local import (Finding, _assigned_names, _dotted, _is_lockish,
                    _iter_own_exprs)
from .summary import suppressed

LIFECYCLE_RULES: Set[str] = {"GC030", "GC031", "GC032", "GC033"}

# -- abstract tokens --------------------------------------------------------
BOT = "BOT"      # not acquired on this path
ACQ = "ACQ"      # acquired and held
PAR = "PAR"      # held by a parameter (caller owns it; we may release)
REL = "REL"      # released
RELX = "RELX"    # the release itself raised and was swallowed (best-effort)
ESC = "ESC"      # ownership transferred (return / self-store / owning callee)
# ("SW", handler_line)   — ACQ that survived into a swallowing except
# ("SWP", handler_line)  — PAR that survived into a swallowing except
# ("PESC", callee, pos)  — passed to an unresolved callee (pending)

_KIND_DESC = {
    "pool": "block-pool allocation",
    "channel": "store channel segment",
    "group": "collective group",
    "lock": "lock",
    "file": "file/socket handle",
}

_FILE_CTOR_NAMES = {
    ("open",), ("io", "open"), ("socket", "socket"),
    ("socket", "create_connection"),
}

_BENIGN_CALLEES = {
    "len", "str", "repr", "int", "float", "bool", "sorted", "list",
    "tuple", "set", "dict", "frozenset", "min", "max", "sum", "any",
    "all", "enumerate", "zip", "isinstance", "print", "id", "hash",
    "format", "iter", "next", "reversed", "range", "abs", "map",
    "filter", "getattr", "hasattr", "type",
}


def _poolish(recv: ast.AST) -> bool:
    d = _dotted(recv)
    return d is not None and any("pool" in part.lower() for part in d)


def _ctor_like(callee: str) -> bool:
    """CamelCase (or _CamelCase) final component = a class constructor."""
    last = callee.split(".")[-1].lstrip("_")
    return bool(last[:1].isupper())


def _recv_dotted(recv: ast.AST) -> Optional[str]:
    d = _dotted(recv)
    return ".".join(d) if d else None


def classify_call(call: ast.Call, known_locks: Set[str]
                  ) -> Optional[Tuple[str, ...]]:
    """One call expression -> a lifecycle op, or None.

    ("acquire", kind, mode)            mode: value | arg0
    ("retain",)                        pool refcount++ on arg0
    ("release", kind, "arg")           releases arg0's resource(s)
    ("release", "group", "kindwide")   destroy releases every group rid
    ("acquire"/"release", "lock", "recv", dotted)
    ("close",)                         .close() on a tracked value
    """
    func = call.func
    if isinstance(func, ast.Attribute):
        attr = func.attr
        recv = func.value
        if attr == "alloc" and _poolish(recv):
            return ("acquire", "pool", "value")
        if attr == "retain" and _poolish(recv):
            return ("retain",)
        if attr == "free" and _poolish(recv):
            return ("release", "pool", "arg")
        if attr == "allocate_channel":
            return ("acquire", "channel", "arg0")
        if attr == "release_channel":
            return ("release", "channel", "arg")
        if attr in ("acquire", "release") and _is_lockish(recv, known_locks):
            dotted = _recv_dotted(recv)
            if dotted:
                return (attr if attr == "acquire" else "release",
                        "lock", "recv", dotted)
        if attr == "close":
            return ("close",)
    d = _dotted(func)
    if d is not None:
        if d[-1] == "create_collective_group":
            return ("acquire", "group", "value")
        if d[-1] == "destroy_collective_group":
            return ("release", "group", "kindwide")
        if d in _FILE_CTOR_NAMES:
            return ("acquire", "file", "value")
    return None


def _walk_expr(root: ast.AST):
    """`root` plus every sub-expression, pruning nested scopes."""
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
                stack.append(child)


def _calls_in(node: ast.AST) -> List[ast.Call]:
    it = _iter_own_exprs(node) if isinstance(node, ast.stmt) \
        else _walk_expr(node)
    return [n for n in it if isinstance(n, ast.Call)]


# ---------------------------------------------------------------------------
# per-module ownership oracle


def collect_functions(tree: ast.Module
                      ) -> List[Tuple[ast.AST, str, Optional[str]]]:
    """(fndef, qname, class) triples with the same qname scheme the
    summary extractor uses ("fn", "Cls.m", "fn.inner")."""
    out: List[Tuple[ast.AST, str, Optional[str]]] = []

    def visit_stmts(stmts, qprefix: str, cls: Optional[str]) -> None:
        for d in _child_defs(stmts):
            if isinstance(d, ast.ClassDef):
                visit_class(d)
            else:
                out.append((d, qprefix + d.name, cls))
                visit_stmts(d.body, qprefix + d.name + ".", cls)

    def visit_class(c: ast.ClassDef) -> None:
        for m in _child_defs(c.body):
            if isinstance(m, ast.ClassDef):
                visit_class(m)
            else:
                out.append((m, f"{c.name}.{m.name}", c.name))
                visit_stmts(m.body, f"{c.name}.{m.name}.", c.name)

    visit_stmts(tree.body, "", None)
    return out


def _child_defs(stmts: Sequence[ast.stmt]) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    stack: List[ast.stmt] = list(stmts)
    while stack:
        st = stack.pop(0)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            out.append(st)
            continue
        for fld in ("body", "orelse", "finalbody"):
            child = getattr(st, fld, None)
            if isinstance(child, list):
                stack.extend(c for c in child if isinstance(c, ast.stmt))
        for handler in getattr(st, "handlers", ()):
            stack.extend(handler.body)
        for case in getattr(st, "cases", ()):
            stack.extend(case.body)
    return out


def _own_scope_stmts(fndef: ast.AST):
    """Every statement in the function's own scope (nested defs pruned)."""
    stack: List[ast.stmt] = list(fndef.body)
    while stack:
        st = stack.pop()
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        yield st
        for fld in ("body", "orelse", "finalbody"):
            child = getattr(st, fld, None)
            if isinstance(child, list):
                stack.extend(c for c in child if isinstance(c, ast.stmt))
        for handler in getattr(st, "handlers", ()):
            stack.extend(handler.body)
        for case in getattr(st, "cases", ()):
            stack.extend(case.body)


def _params_of(fndef: ast.AST) -> List[str]:
    a = fndef.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def _resolve_local(oracle: Dict[str, Dict[str, Any]], callee: str,
                   cls: Optional[str]) -> Optional[Tuple[str, int]]:
    """Callee name as written -> (oracle qname, arg->param offset)."""
    if callee.startswith("self.") and cls:
        q = f"{cls}.{callee[5:]}"
        return (q, 1) if q in oracle else None
    if "." in callee:
        return None
    return (callee, 0) if callee in oracle else None


def build_ownership_oracle(tree: ast.Module, known_locks: Set[str]
                           ) -> Dict[str, Dict[str, Any]]:
    """qname -> {"params", "releases" (param idxs), "owns" (param idxs),
    "self_releases" (dotted lock receivers released)}.

    "releases" closes over same-module helper chains (3-round fixpoint);
    "owns" = param returned, stored on self/a container, or appended.
    """
    fns = collect_functions(tree)
    oracle: Dict[str, Dict[str, Any]] = {}
    bodies: Dict[str, Tuple[ast.AST, Optional[str]]] = {}
    for fndef, qname, cls in fns:
        # "escapes": params handed to a callee THIS module cannot
        # resolve — the function is then NOT provably non-owning, so a
        # pending leak through it must stay silent instead of
        # confirming (a one-hop delegation chain ends in another file)
        oracle[qname] = {"params": _params_of(fndef), "releases": set(),
                         "owns": set(), "self_releases": set(),
                         "escapes": set()}
        bodies[qname] = (fndef, cls)

    helper_sites: Dict[str, List[Tuple[ast.Call, Optional[str]]]] = {}
    for qname, (fndef, cls) in bodies.items():
        rec = oracle[qname]
        pidx = {p: i for i, p in enumerate(rec["params"])}
        # `for b in blocks:` makes b an elementwise view of the param —
        # releasing b inside the loop releases the param's resources
        # (the free_all(pool, blocks) cleanup-helper idiom)
        for st in _own_scope_stmts(fndef):
            if isinstance(st, (ast.For, ast.AsyncFor)) \
                    and isinstance(st.iter, ast.Name) \
                    and st.iter.id in pidx:
                for nm in _assigned_names(st.target):
                    pidx.setdefault(nm, pidx[st.iter.id])
        sites: List[Tuple[ast.Call, Optional[str]]] = []
        for stmt in _own_scope_stmts(fndef):
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                # structural "returns the param" forms only — a param
                # merely READ inside the return expression (len(p),
                # sum(x for x in p)) does not transfer ownership out
                for n in _returned_names(stmt.value):
                    if n in pidx:
                        rec["owns"].add(pidx[n])
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                value = stmt.value
                if value is not None and any(
                        isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in targets):
                    for n in ast.walk(value):
                        if isinstance(n, ast.Name) and n.id in pidx:
                            rec["owns"].add(pidx[n.id])
            for node in _calls_in(stmt):
                op = classify_call(node, known_locks)
                if op is not None:
                    if op[0] == "release" and op[1] == "lock":
                        rec["self_releases"].add(op[3])
                    elif op[0] == "release" and op[-1] == "arg":
                        for a in _release_arg_names(node):
                            if a in pidx:
                                rec["releases"].add(pidx[a])
                    elif op[0] == "close":
                        recv = node.func.value
                        if isinstance(recv, ast.Name) and recv.id in pidx:
                            rec["releases"].add(pidx[recv.id])
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in ("close", "release") \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id in pidx:
                    rec["releases"].add(pidx[func.value.id])
                    continue
                if isinstance(func, ast.Attribute) \
                        and func.attr == "append":
                    for a in node.args:
                        if isinstance(a, ast.Name) and a.id in pidx:
                            rec["owns"].add(pidx[a.id])
                    continue
                sites.append((node, cls))
        helper_sites[qname] = sites

    def _arg_params(node: ast.Call, pidx: Dict[str, int]):
        """(arg-position-or-param-name, param index) pairs for every
        param handed to `node`, positionals AND keywords."""
        out: List[Tuple[Any, int]] = []
        for pos, a in enumerate(node.args):
            if isinstance(a, ast.Name) and a.id in pidx:
                out.append((pos, pidx[a.id]))
        for kw in node.keywords:
            if kw.arg and isinstance(kw.value, ast.Name) \
                    and kw.value.id in pidx:
                out.append((kw.arg, pidx[kw.value.id]))
        return out

    # params escaping to callees this module cannot see through
    for qname, sites in helper_sites.items():
        rec = oracle[qname]
        pidx = {p: i for i, p in enumerate(rec["params"])}
        for node, cls in sites:
            d = _dotted(node.func)
            callee = ".".join(d) if d else None
            if callee is not None \
                    and _resolve_local(oracle, callee, cls) is not None:
                continue
            if callee is not None and (
                    callee in _BENIGN_CALLEES
                    or callee.split(".")[-1] in _BENIGN_CALLEES):
                continue
            for _, p in _arg_params(node, pidx):
                rec["escapes"].add(p)

    # close releases/owns over same-module helper chains; a param
    # passed into a constructor counts as owned by the object
    for _ in range(3):
        changed = False
        for qname, sites in helper_sites.items():
            rec = oracle[qname]
            pidx = {p: i for i, p in enumerate(rec["params"])}
            for node, cls in sites:
                d = _dotted(node.func)
                if d is None:
                    continue
                callee = ".".join(d)
                hit = _resolve_local(oracle, callee, cls)
                if hit is None:
                    if _ctor_like(callee) \
                            and callee not in _BENIGN_CALLEES:
                        for a in list(node.args) + \
                                [k.value for k in node.keywords]:
                            if isinstance(a, ast.Name) and a.id in pidx \
                                    and pidx[a.id] not in rec["owns"]:
                                rec["owns"].add(pidx[a.id])
                                changed = True
                    continue
                cq, off = hit
                crec = oracle[cq]
                for key, p in _arg_params(node, pidx):
                    if isinstance(key, int):
                        cidx = key + off
                    elif key in crec["params"]:
                        cidx = crec["params"].index(key)
                    else:
                        continue
                    if cidx in crec["releases"] \
                            and p not in rec["releases"]:
                        rec["releases"].add(p)
                        changed = True
                    if cidx in crec["owns"] and p not in rec["owns"]:
                        rec["owns"].add(p)
                        changed = True
                    if cidx in crec["escapes"] and p not in rec["escapes"]:
                        rec["escapes"].add(p)
                        changed = True
        if not changed:
            break
    return oracle


def _returned_names(value: ast.AST) -> List[str]:
    """Names a return expression hands to the caller structurally:
    bare names, tuple/list/set elements, dict values, either arm of a
    conditional — not names merely read inside calls/comprehensions."""
    if isinstance(value, ast.Name):
        return [value.id]
    if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
        out: List[str] = []
        for e in value.elts:
            out.extend(_returned_names(e))
        return out
    if isinstance(value, ast.Dict):
        out = []
        for v in value.values:
            out.extend(_returned_names(v))
        return out
    if isinstance(value, ast.IfExp):
        return _returned_names(value.body) + _returned_names(value.orelse)
    return []


def _release_arg_names(call: ast.Call) -> List[str]:
    """Names released by a ("release", kind, "arg") call: a bare Name
    arg or a list/tuple literal of Names."""
    if not call.args:
        return []
    a = call.args[0]
    if isinstance(a, ast.Name):
        return [a.id]
    if isinstance(a, (ast.List, ast.Tuple)):
        return [e.id for e in a.elts if isinstance(e, ast.Name)]
    return []


# ---------------------------------------------------------------------------
# resource ids


class _Rid:
    __slots__ = ("idx", "kind", "line", "col", "mode", "name", "recv",
                 "accum")

    def __init__(self, idx: int, kind: str, line: int, col: int, mode: str,
                 name: Optional[str] = None, recv: Optional[str] = None):
        self.idx = idx
        self.kind = kind
        self.line = line
        self.col = col
        self.mode = mode          # value | arg | recv | param | with
        self.name = name          # bound variable name when known
        self.recv = recv          # receiver dotted path (lock rids)
        self.accum = False        # flows into an accumulator container

    @property
    def desc(self) -> str:
        return _KIND_DESC[self.kind]


# ---------------------------------------------------------------------------
# the dataflow domain


class _LifecycleDomain:
    """State = (env, res): env maps local names to frozensets of rid
    indices, res is a tuple with one frozenset of tokens per rid.
    States are never mutated in place — `transfer` copies before
    changing anything, since inputs are shared between edges."""

    def __init__(self, analyzer: "_FunctionAnalysis"):
        self.a = analyzer

    # -- lattice -----------------------------------------------------------

    def initial(self):
        env: Dict[str, Any] = {}
        res = []
        for rid in self.a.rids:
            if rid.mode == "param":
                res.append(frozenset({PAR}))
                env[rid.name] = env.get(rid.name, frozenset()) | {rid.idx}
            else:
                res.append(frozenset({BOT}))
        return (env, tuple(res))

    def join(self, s1, s2):
        if s1 == s2:
            return s1
        env1, res1 = s1
        env2, res2 = s2
        env = dict(env1)
        for k, v in env2.items():
            env[k] = env.get(k, frozenset()) | v
        res = tuple(a | b for a, b in zip(res1, res2))
        return (env, res)

    def assume(self, state, label):
        sense, name = label
        env, res = state
        if sense in ("held", "unheld"):
            # try-acquire condition: `name` is the lock's dotted receiver
            rid = self.a.rid_by_recv.get(name)
            if rid is None:
                return state
            out = list(res)
            if sense == "unheld":
                out[rid] = frozenset({BOT})
            elif BOT in out[rid] and len(out[rid]) > 1:
                out[rid] = out[rid] - {BOT}
            return (env, tuple(out))
        rids = env.get(name)
        if not rids:
            return state
        out = list(res)
        changed = False
        for i in rids:
            if sense == "none":
                # on this path the name is None: the acquire bound to
                # it produced nothing
                if out[i] != frozenset({BOT}):
                    out[i] = frozenset({BOT})
                    changed = True
            elif BOT in out[i] and len(out[i]) > 1:
                out[i] = out[i] - {BOT}
                changed = True
        return (env, tuple(out)) if changed else state

    # -- exception-edge refinement ----------------------------------------

    def exc_edge(self, node, state):
        """A pure-release statement raising: the resource is released-
        or-failed-releasing (best-effort close) — not a leak path."""
        if node.kind != STMT or not isinstance(node.ast, ast.Expr) \
                or not isinstance(node.ast.value, ast.Call):
            return state
        op = classify_call(node.ast.value, self.a.known_locks)
        if op is None or op[0] not in ("release", "close"):
            return state
        env, res = state
        targets = self._release_targets(node.ast.value, op, env)
        if not targets:
            return state
        out = list(res)
        changed = False
        for i in targets:
            if ACQ in out[i] or PAR in out[i]:
                out[i] = (out[i] - {ACQ, PAR}) | {RELX}
                changed = True
        return (env, tuple(out)) if changed else state

    # -- transfer ----------------------------------------------------------

    def transfer(self, node, state):
        kind = node.kind
        if kind in (ENTRY, RAISE_EXIT, EXCEPT_DISPATCH):
            return state
        if kind == EXIT:
            self.a.report_exit(state)
            return state
        if kind == EXCEPT_ENTRY:
            return self._except_entry(node, state)
        if kind == WITH_ENTER:
            return self._with_enter(node, state)
        if kind == WITH_EXIT:
            return self._with_exit(node, state)
        env, res = dict(state[0]), list(state[1])
        if kind == FOR_BIND:
            self._rebind(_assigned_names(node.ast.target), env, res,
                         node.lineno, protect=())
        elif kind == TEST:
            self._process_calls(node.ast, None, env, res)
        else:
            self._stmt(node.ast, env, res)
        return (env, tuple(res))

    # -- statement transfer ------------------------------------------------

    def _stmt(self, stmt, env, res) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            top_rid = self._process_calls(stmt, value, env, res)
            name_targets: List[str] = []
            attr_store = False
            for t in targets:
                names = _assigned_names(t)
                if names:
                    name_targets.extend(names)
                else:
                    attr_store = True
            if attr_store and value is not None:
                # self.x = b / d[k] = b: ownership transferred
                self._escape_names(value, env, res)
                if top_rid is not None and not any(
                        _assigned_names(t) for t in targets):
                    # self.x = open(...): acquired straight into a field
                    res[top_rid] = frozenset({ESC})
                    top_rid = None
            alias = None
            if isinstance(value, ast.Name) and len(name_targets) == 1:
                alias = env.get(value.id)
            self._rebind(name_targets, env, res, stmt.lineno,
                         protect=(top_rid,) if top_rid is not None else ())
            if name_targets:
                if alias:
                    env[name_targets[0]] = alias
                elif top_rid is not None:
                    for n in name_targets:
                        env[n] = env.get(n, frozenset()) | {top_rid}
        elif isinstance(stmt, ast.Return):
            top_rid = self._process_calls(stmt, stmt.value, env, res)
            if top_rid is not None:
                res[top_rid] = frozenset({ESC})  # ownership to the caller
            if stmt.value is not None:
                self._escape_names(stmt.value, env, res)
        else:
            self._process_calls(stmt, None, env, res)

    # -- pieces ------------------------------------------------------------

    def _process_calls(self, node, top_value, env, res) -> Optional[int]:
        """Run lifecycle ops for every call in the node's own
        expressions. Returns the rid acquired by the `top_value` call
        (to be bound by the caller), if any."""
        a = self.a
        top_rid: Optional[int] = None
        none_calls = _none_asserted_calls(node)
        for call in _calls_in(node):
            if id(call) in a.expect_raise:
                continue
            op = classify_call(call, a.known_locks)
            if op is None:
                self._helper_call(call, env, res)
                continue
            if id(call) in none_calls:
                # `assert pool.alloc(5) is None`: the acquisition is
                # proven to have FAILED on the continuing path
                rid = a.rid_by_call.get(id(call))
                if rid is not None:
                    res[rid] = frozenset({BOT})
                continue
            tag = op[0]
            if tag == "acquire" and op[1] == "lock":
                rid = a.rid_by_recv.get(op[3])
                if rid is not None:
                    res[rid] = frozenset({ACQ})
                    if call is top_value:
                        # `got = lock.acquire(timeout=...)`: bind the
                        # result name so `if got:` branches refine the
                        # lock's state like any None-guard
                        top_rid = rid
            elif tag == "acquire":
                rid = a.rid_by_call.get(id(call))
                if rid is None:
                    continue
                r = a.rids[rid]
                bound = call is top_value
                if r.kind == "file" and not bound:
                    continue  # only track name-bound opens
                if ACQ in res[rid] and not r.accum:
                    a.report(
                        "GC030", r.line, r.col,
                        f"{r.desc} re-acquired here while a previous "
                        f"acquisition from this site is still held on "
                        f"the looping path — the earlier resource "
                        f"leaks; release it before re-acquiring")
                res[rid] = frozenset({ACQ})
                if r.mode == "arg":
                    if r.name is not None:
                        env[r.name] = env.get(r.name, frozenset()) | {rid}
                elif bound:
                    top_rid = rid
                elif r.kind == "pool" and isinstance(node, ast.Expr) \
                        and node.value is call:
                    a.report(
                        "GC030", r.line, r.col,
                        f"result of this {r.desc} is discarded — the "
                        f"blocks can never be released; bind the result "
                        f"and pair it with a release")
            elif tag == "retain":
                rid = a.rid_by_call.get(id(call))
                if rid is None:
                    continue
                nm = a.rids[rid].name
                # use-after-release only when NOTHING bound to the name
                # is still held: with the refcount model an alloc-rid
                # can legally stay live while an earlier retain-rid was
                # consumed by a free (alloc;retain;free;retain is rc
                # 1-2-1-2 — balanced, not a UAF)
                others = [r0 for r0 in env.get(nm, ()) if r0 != rid]
                if others and all(res[r0] == frozenset({REL})
                                  for r0 in others):
                    a.report(
                        "GC031", call.lineno, call.col_offset + 1,
                        f"'{nm}' is retained here after being "
                        f"released on every incoming path "
                        f"(use-after-release)")
                res[rid] = frozenset({ACQ})
                env[nm] = env.get(nm, frozenset()) | {rid}
            else:  # release / close
                self._release_selected(
                    call, self._release_targets(call, op, env), res)
        return top_rid

    def _release_selected(self, call, targets: List[int], res) -> None:
        """Release through the refcount model: several acquisitions
        (alloc + retains) sharing one name mean one free consumes ONE
        outstanding acquisition — release the latest still-held one;
        only a free with nothing left held is a double release."""
        a = self.a
        if len(targets) > 1:
            live = [rid for rid in targets
                    if ACQ in res[rid] or _has_sw(res[rid])
                    or any(isinstance(t, tuple) and t[0] == "PESC"
                           for t in res[rid])]
            pick = max(live or targets, key=lambda i: a.rids[i].line)
            self._do_release(call, pick, res, rc_ambiguous=True)
        else:
            for rid in targets:
                self._do_release(call, rid, res)

    def _release_targets(self, call, op, env) -> List[int]:
        a = self.a
        if op[0] == "close":
            recv = call.func.value
            if isinstance(recv, ast.Name):
                return [i for i in env.get(recv.id, ())
                        if a.rids[i].kind == "file"]
            return []
        if op[1] == "lock":
            rid = a.rid_by_recv.get(op[3])
            return [rid] if rid is not None else []
        if op[-1] == "kindwide":
            return [r.idx for r in a.rids if r.kind == "group"]
        out: List[int] = []
        for nm in _release_arg_names(call):
            out.extend(env.get(nm, ()))
        return out

    def _do_release(self, call, rid: int, res,
                    rc_ambiguous: bool = False) -> None:
        a = self.a
        r = a.rids[rid]
        tokens = res[rid]
        line, col = call.lineno, call.col_offset + 1
        if REL in tokens:
            a.report(
                "GC031", line, col,
                f"{r.desc}{_at(r)} is released again here after an "
                f"earlier release on some incoming path — double "
                f"release (refcount corruption / unheld-lock error)")
        pesc = [t for t in tokens
                if isinstance(t, tuple) and t[0] == "PESC"]
        if pesc:
            a.pending(
                "GC031", line, col,
                callees=[(t[1], t[2]) for t in pesc], confirm="releases",
                message=f"{r.desc}{_at(r)} is released here after being "
                        f"passed to {{callee}}(), which also releases it "
                        f"(resolved project-wide) — double release")
        if not rc_ambiguous and not r.accum and BOT in tokens \
                and (ACQ in tokens or _has_sw(tokens)):
            a.report(
                "GC033", line, col,
                f"{r.desc}{_at(r)} is released here unconditionally but "
                f"acquired only on some incoming paths — on the path "
                f"that skipped the acquire this releases an unheld "
                f"resource; mirror the acquire/release branch structure")
        res[rid] = frozenset({REL})

    def _helper_call(self, call, env, res) -> None:
        a = self.a
        d = _dotted(call.func)
        callee = ".".join(d) if d else None
        func = call.func
        if isinstance(func, ast.Attribute) \
                and func.attr in ("append", "extend") \
                and isinstance(func.value, ast.Name):
            # acc.extend(pool.alloc(1)): the acquisition accumulates
            # into `acc` — bind the rid there so a later free(acc)
            # releases it, and mark it re-acquirable (loop pattern)
            linked = False
            for arg in call.args:
                if isinstance(arg, ast.Call):
                    rid = a.rid_by_call.get(id(arg))
                    if rid is not None:
                        a.rids[rid].accum = True
                        nm = func.value.id
                        env[nm] = env.get(nm, frozenset()) | {rid}
                        linked = True
            if linked:
                return
        # positionals keyed by index, keywords by name — a resource
        # passed as `_Seq(blocks=b)` transfers ownership like `_Seq(b)`
        tracked = [(pos, arg.id) for pos, arg in enumerate(call.args)
                   if isinstance(arg, ast.Name) and env.get(arg.id)]
        tracked += [(kw.arg, kw.value.id) for kw in call.keywords
                    if kw.arg and isinstance(kw.value, ast.Name)
                    and env.get(kw.value.id)]
        hit = _resolve_local(a.oracle, callee, a.cls) \
            if callee and a.oracle else None
        if hit is not None:
            cq, off = hit
            crec = a.oracle[cq]
            for key, nm in tracked:
                if isinstance(key, int):
                    p = key + off
                elif key in crec["params"]:
                    p = crec["params"].index(key)
                else:
                    continue
                if p in crec["releases"]:
                    # same consume-one refcount semantics as a direct
                    # free — a helper-routed free must not drain every
                    # acquisition bound to the name at once
                    self._release_selected(call, list(env.get(nm, ())),
                                           res)
                elif p in crec["owns"] or p in crec["escapes"]:
                    # owns = transferred; escapes = the helper hands it
                    # to a callee IT cannot see — not provable either
                    # way, stay silent
                    for rid in env.get(nm, ()):
                        if ACQ in res[rid]:
                            res[rid] = (res[rid] - {ACQ}) | {ESC}
            if callee.startswith("self."):
                # a helper releasing self-held locks releases them here
                for dotted in crec["self_releases"]:
                    rid = a.rid_by_recv.get(dotted)
                    if rid is not None:
                        self._do_release(call, rid, res)
            return
        if not tracked:
            return
        if callee is None:
            for _, nm in tracked:
                for rid in env.get(nm, ()):
                    if ACQ in res[rid]:
                        res[rid] = (res[rid] - {ACQ}) | {ESC}
            return
        if callee in _BENIGN_CALLEES \
                or callee.split(".")[-1] in _BENIGN_CALLEES:
            return
        if isinstance(func, ast.Attribute) and _poolish(func.value):
            # a pool method that is not alloc/retain/free is a query
            # (refcount, used_count, check_leaks): no ownership change
            return
        if _ctor_like(callee):
            # Cls(b) / _Seq(blocks=b): the object takes ownership
            for _, nm in tracked:
                for rid in env.get(nm, ()):
                    if ACQ in res[rid]:
                        res[rid] = (res[rid] - {ACQ}) | {ESC}
            return
        for key, nm in tracked:
            for rid in env.get(nm, ()):
                if ACQ not in res[rid]:
                    continue
                if isinstance(key, int):
                    # pending: the project pass may still prove a leak
                    res[rid] = (res[rid] - {ACQ}) | {("PESC", callee, key)}
                else:
                    # kwarg to an unresolved callee: silent transfer
                    res[rid] = (res[rid] - {ACQ}) | {ESC}

    def _escape_names(self, value: ast.AST, env, res) -> None:
        for n in _walk_expr(value):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                for rid in env.get(n.id, ()):
                    if ACQ in res[rid]:
                        res[rid] = (res[rid] - {ACQ}) | {ESC}

    def _rebind(self, names: List[str], env, res, lineno: int,
                protect: Tuple) -> None:
        a = self.a
        for n in names:
            old = env.pop(n, None)
            if not old:
                continue
            others: Set[int] = set()
            for v in env.values():
                others.update(v)
            for rid in old:
                r = a.rids[rid]
                if rid in protect or rid in others:
                    continue
                if r.mode not in ("value", "arg"):
                    continue
                # only claim an orphan when NO path handled the
                # resource (a REL/ESC on some path means ownership is
                # managed through state the env cannot see)
                if ACQ in res[rid] and REL not in res[rid] \
                        and ESC not in res[rid]:
                    a.report(
                        "GC030", lineno, 1,
                        f"rebinding '{n}' here orphans the unreleased "
                        f"{r.desc} acquired at line {r.line} — release "
                        f"it before reusing the name")
                # the binding is gone: reset the site so a stale REL
                # from a previous loop iteration cannot fake a GC031
                # against the next binding
                res[rid] = frozenset({BOT})

    def _except_entry(self, node, state):
        handler = node.ast
        env, res = state
        if handler.name:
            env = dict(env)
            env.pop(handler.name, None)
        if not handler_swallows(handler):
            return (env, res)
        hline = handler.lineno
        out = list(res)
        changed = False
        for i, tokens in enumerate(out):
            nt = tokens
            if ACQ in nt:
                nt = (nt - {ACQ}) | {("SW", hline)}
            if PAR in nt:
                nt = (nt - {PAR}) | {("SWP", hline)}
            if nt is not tokens:
                out[i] = nt
                changed = True
        return (env, tuple(out)) if changed else (env, res)

    def _with_enter(self, node, state):
        rid = self.a.rid_by_item.get(id(node.ast))
        if rid is None:
            return state
        env, res = dict(state[0]), list(state[1])
        res[rid] = frozenset({ACQ})
        opt = node.ast.optional_vars
        if isinstance(opt, ast.Name):
            env[opt.id] = frozenset({rid})
        return (env, tuple(res))

    def _with_exit(self, node, state):
        a = self.a
        rid = a.rid_by_item.get(id(node.ast))
        if rid is None:
            return state
        env, res = state
        r = a.rids[rid]
        if REL in res[rid] and r.kind == "lock":
            a.report(
                "GC031", node.lineno, 1,
                f"this with block releases the {r.desc} on exit, but it "
                f"was already released manually inside the block on "
                f"some path — double release (unheld-lock error)")
        out = list(res)
        out[rid] = frozenset({REL})
        return (env, tuple(out))


def _at(r: _Rid) -> str:
    return f" ('{r.name}')" if r.mode == "param" \
        else f" acquired at line {r.line}"


def _has_sw(tokens) -> bool:
    return any(isinstance(t, tuple) and t[0] == "SW" for t in tokens)


def _none_asserted_calls(node: ast.AST) -> frozenset:
    """id()s of calls proven failed by `assert <call> is None` (and the
    equivalent `assert <call> == None`)."""
    if not isinstance(node, ast.Assert):
        return frozenset()
    out = set()
    for n in _walk_expr(node.test):
        if isinstance(n, ast.Compare) and len(n.ops) == 1 \
                and isinstance(n.ops[0], (ast.Is, ast.Eq)) \
                and isinstance(n.left, ast.Call) \
                and isinstance(n.comparators[0], ast.Constant) \
                and n.comparators[0].value is None:
            out.add(id(n.left))
    return frozenset(out)


# ---------------------------------------------------------------------------
# per-function analysis driver


class _FunctionAnalysis:
    def __init__(self, fndef: ast.AST, qname: str, cls: Optional[str],
                 summary: Dict[str, Any], known_locks: Set[str],
                 oracle: Dict[str, Dict[str, Any]],
                 findings: List[Finding], pendings: List[Dict[str, Any]]):
        self.fndef = fndef
        self.qname = qname
        self.cls = cls
        self.summary = summary
        self.known_locks = known_locks
        self.oracle = oracle
        self.findings = findings
        self.pendings = pendings
        self.rids: List[_Rid] = []
        self.rid_by_call: Dict[int, int] = {}
        self.rid_by_recv: Dict[str, int] = {}
        self.rid_by_item: Dict[int, int] = {}
        self.expect_raise: Set[int] = set()
        self.release_lines: Dict[str, List[int]] = {}
        self.any_release_lines: List[int] = []
        self._reported: Set[Tuple] = set()
        self._pending_keys: Set[Tuple] = set()

    # -- reporting ---------------------------------------------------------

    def report(self, rule: str, line: int, col: int, message: str) -> None:
        key = (rule, line, message[:48])
        if key in self._reported:
            return
        if suppressed(self.summary, line, rule):
            return
        self._reported.add(key)
        self.findings.append(Finding(
            path=self.summary["path"], line=line, col=col, rule=rule,
            message=message))

    def pending(self, rule: str, line: int, col: int,
                callees: List[Tuple[str, int]], confirm: str,
                message: str) -> None:
        key = (rule, line, tuple(sorted(callees)))
        if key in self._pending_keys:
            return
        if suppressed(self.summary, line, rule):
            return
        self._pending_keys.add(key)
        self.pendings.append({
            "rule": rule, "line": line, "col": col, "fn": self.qname,
            "callees": sorted(set(callees)), "confirm": confirm,
            "message": message,
        })

    def report_exit(self, state) -> None:
        _env, res = state
        for rid, tokens in zip(self.rids, res):
            if rid.mode == "param":
                swp = [t for t in tokens
                       if isinstance(t, tuple) and t[0] == "SWP"]
                if swp and self._has_release(rid.kind):
                    self.report(
                        "GC032", self._first_release(rid.kind), 1,
                        f"the release of '{rid.name}' here is skipped "
                        f"when the except at line {swp[0][1]} swallows "
                        f"an exception raised before it — the path "
                        f"rejoins the normal flow with the {rid.desc} "
                        f"unreleased; move the release into a finally "
                        f"block")
                continue
            if ACQ in tokens:
                self.report(
                    "GC030", rid.line, rid.col,
                    f"{rid.desc} acquired here is not released on every "
                    f"path: a normal exit is reachable with it still "
                    f"held — release it in try/finally, store it on "
                    f"self, or return it to transfer ownership")
                continue
            sw = [t for t in tokens
                  if isinstance(t, tuple) and t[0] == "SW"]
            if sw:
                if self._has_release(rid.kind):
                    self.report(
                        "GC032", self._first_release(rid.kind), 1,
                        f"the release of the {rid.desc} acquired at "
                        f"line {rid.line} is skipped when the except at "
                        f"line {sw[0][1]} swallows an exception raised "
                        f"before it — the path rejoins the normal flow "
                        f"with the resource unreleased; move the "
                        f"release into a finally block")
                else:
                    self.report(
                        "GC030", rid.line, rid.col,
                        f"{rid.desc} acquired here leaks through the "
                        f"swallowing except at line {sw[0][1]}: the "
                        f"exception path rejoins the normal flow with "
                        f"it unreleased and no release exists — release "
                        f"in try/finally")
                continue
            pesc = [t for t in tokens
                    if isinstance(t, tuple) and t[0] == "PESC"]
            if pesc:
                self.pending(
                    "GC030", rid.line, rid.col,
                    callees=[(t[1], t[2]) for t in pesc],
                    confirm="none_own",
                    message=f"{rid.desc} acquired here is passed to "
                            f"{{callee}}(), which neither releases nor "
                            f"takes ownership of it (resolved "
                            f"project-wide), and is never released on "
                            f"some path — a leak")

    def _has_release(self, kind: str) -> bool:
        return bool(self.release_lines.get(kind) or self.any_release_lines)

    def _first_release(self, kind: str) -> int:
        lines = self.release_lines.get(kind) or self.any_release_lines
        return min(lines)

    # -- pre-scan ----------------------------------------------------------

    def prescan(self) -> bool:
        """Enumerate resource ids; False when nothing is trackable."""
        params = set(_params_of(self.fndef))
        param_rids: Dict[str, int] = {}
        with_calls: Set[int] = set()
        # statement order here is arbitrary (stack walk): track the
        # earliest ACQUIRE site per lock receiver so the rid anchors at
        # the acquire, not at whichever release happened to be seen first
        lock_acq_line: Dict[str, int] = {}

        def new_rid(**kw) -> int:
            rid = _Rid(idx=len(self.rids), **kw)
            self.rids.append(rid)
            return rid.idx

        for stmt in _own_scope_stmts(self.fndef):
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call):
                        with_calls.add(id(ce))
                        d0 = _dotted(ce.func)
                        if d0 is not None and d0[-1] == "raises":
                            # `with pytest.raises(...):` — every
                            # lifecycle op inside is EXPECTED to fail;
                            # tracking it would report the test's own
                            # intent (parents are yielded before their
                            # body statements, so this fills in time)
                            for body_stmt in stmt.body:
                                for sub in ast.walk(body_stmt):
                                    if isinstance(sub, ast.Call):
                                        self.expect_raise.add(id(sub))
                        op = classify_call(ce, self.known_locks)
                        if op and op[0] == "acquire" \
                                and op[1] in ("file", "pool"):
                            self.rid_by_item[id(item)] = new_rid(
                                kind=op[1], line=ce.lineno,
                                col=ce.col_offset + 1, mode="with")
                    elif _is_lockish(ce, self.known_locks):
                        dotted = _recv_dotted(ce)
                        if dotted:
                            rid = self.rid_by_recv.get(dotted)
                            if rid is None:
                                rid = new_rid(kind="lock", line=ce.lineno,
                                              col=ce.col_offset + 1,
                                              mode="with", recv=dotted)
                                self.rid_by_recv[dotted] = rid
                            self.rid_by_item[id(item)] = rid
            for expr in _calls_in(stmt):
                if id(expr) in with_calls or id(expr) in self.expect_raise:
                    continue
                op = classify_call(expr, self.known_locks)
                if op is None:
                    # a local helper releasing an arg still counts as a
                    # release site for the GC032 "release exists" gate
                    d = _dotted(expr.func)
                    if d is not None and self.oracle:
                        hit = _resolve_local(self.oracle, ".".join(d),
                                             self.cls)
                        if hit is not None:
                            crel = self.oracle[hit[0]]["releases"]
                            for pos, a in enumerate(expr.args):
                                if isinstance(a, ast.Name) \
                                        and (pos + hit[1]) in crel:
                                    self.any_release_lines.append(
                                        expr.lineno)
                    continue
                tag = op[0]
                if tag == "acquire" and op[1] == "lock":
                    if op[3] not in self.rid_by_recv:
                        self.rid_by_recv[op[3]] = new_rid(
                            kind="lock", line=expr.lineno,
                            col=expr.col_offset + 1, mode="recv",
                            recv=op[3])
                    prev = lock_acq_line.get(op[3])
                    if prev is None or expr.lineno < prev:
                        lock_acq_line[op[3]] = expr.lineno
                        r = self.rids[self.rid_by_recv[op[3]]]
                        r.line = expr.lineno
                        r.col = expr.col_offset + 1
                elif tag == "acquire":
                    if op[1] == "pool" and expr.args \
                            and isinstance(expr.args[0], ast.Constant) \
                            and expr.args[0].value == 0:
                        continue  # alloc(0) acquires nothing
                    mode = "value" if op[2] == "value" else "arg"
                    nm = None
                    if mode == "arg":
                        if not (expr.args
                                and isinstance(expr.args[0], ast.Name)):
                            continue
                        nm = expr.args[0].id
                    self.rid_by_call[id(expr)] = new_rid(
                        kind=op[1], line=expr.lineno,
                        col=expr.col_offset + 1, mode=mode, name=nm)
                elif tag == "retain":
                    names = _release_arg_names(expr)
                    if len(names) == 1:  # retain(b) or retain([b])
                        self.rid_by_call[id(expr)] = new_rid(
                            kind="pool", line=expr.lineno,
                            col=expr.col_offset + 1, mode="arg",
                            name=names[0])
                elif tag == "close":
                    recv = expr.func.value
                    if isinstance(recv, ast.Name):
                        self.release_lines.setdefault(
                            "file", []).append(expr.lineno)
                elif tag == "release":
                    if op[1] == "lock":
                        if op[3] not in self.rid_by_recv:
                            self.rid_by_recv[op[3]] = new_rid(
                                kind="lock", line=expr.lineno,
                                col=expr.col_offset + 1, mode="recv",
                                recv=op[3])
                        self.release_lines.setdefault(
                            "lock", []).append(expr.lineno)
                    else:
                        self.release_lines.setdefault(
                            op[1], []).append(expr.lineno)
                        if op[-1] == "arg":
                            for nm in _release_arg_names(expr):
                                if nm in params and nm not in param_rids:
                                    param_rids[nm] = new_rid(
                                        kind=op[1], line=expr.lineno,
                                        col=1, mode="param", name=nm)
        return bool(self.rids)

    # -- run ---------------------------------------------------------------

    def run(self, stats: Dict[str, int]) -> None:
        if not self.prescan():
            stats["fns_trivial"] = stats.get("fns_trivial", 0) + 1
            return
        try:
            graph = build_cfg(self.fndef)
        except CFGTooLarge:
            stats["fns_too_large"] = stats.get("fns_too_large", 0) + 1
            return
        stats["fns_analyzed"] = stats.get("fns_analyzed", 0) + 1
        stats["cfg_nodes"] = stats.get("cfg_nodes", 0) + len(graph.nodes)
        stats["resources"] = stats.get("resources", 0) + len(self.rids)
        result = dataflow.run(graph, _LifecycleDomain(self))
        stats["fixpoint_iterations"] = \
            stats.get("fixpoint_iterations", 0) + result.iterations
        if not result.converged:
            stats["fns_nonconverged"] = \
                stats.get("fns_nonconverged", 0) + 1


# ---------------------------------------------------------------------------
# module entry point (runs at extraction time; results ride the cache)


def analyze_module(tree: ast.Module, summary: Dict[str, Any]
                   ) -> List[Finding]:
    """Path-sensitive GC030-033 over every function of one module.
    Returns the confirmed findings and mutates `summary`:

    - ``summary["lifecycle"] = {"pending": [...], "stats": {...}}``
    - ``summary["functions"][q]["lifecycle"] = {"releases", "owns"}``
      for functions with ownership facts (cross-file resolution).
    """
    findings: List[Finding] = []
    pendings: List[Dict[str, Any]] = []
    stats: Dict[str, int] = {}
    known_locks = set(summary.get("module_unser", ()))
    try:
        oracle = build_ownership_oracle(tree, known_locks)
    except RecursionError:   # pragma: no cover - pathological input
        oracle = {}
    for qname, rec in oracle.items():
        if rec["releases"] or rec["owns"] or rec["escapes"]:
            fnrec = summary["functions"].get(qname)
            if fnrec is not None:
                fnrec["lifecycle"] = {
                    "releases": sorted(rec["releases"]),
                    "owns": sorted(rec["owns"]),
                    "escapes": sorted(rec["escapes"]),
                }
    for fndef, qname, cls in collect_functions(tree):
        stats["fns_total"] = stats.get("fns_total", 0) + 1
        if is_generator(fndef):
            stats["fns_generators_skipped"] = \
                stats.get("fns_generators_skipped", 0) + 1
            continue
        fa = _FunctionAnalysis(fndef, qname, cls, summary, known_locks,
                               oracle, findings, pendings)
        try:
            fa.run(stats)
        except Exception:    # never fail the lint on one function
            stats["fns_errors"] = stats.get("fns_errors", 0) + 1
    summary["lifecycle"] = {"pending": pendings, "stats": stats}
    return findings


# ---------------------------------------------------------------------------
# project pass: resolve pending findings through the import graph


def resolve_pending(index, enabled: Set[str]) -> List[Finding]:
    from .engine import resolve_call_target

    out: List[Finding] = []
    for s in index.summaries:
        lc = s.get("lifecycle") or {}
        for p in lc.get("pending", ()):
            if p["rule"] not in enabled:
                continue
            fnrec = s["functions"].get(p["fn"])
            if fnrec is None:
                continue
            resolved: List[Tuple[str, Dict[str, Any], int]] = []
            all_resolved = True
            for callee, pos in p["callees"]:
                fq = resolve_call_target(index, s, fnrec, callee)
                if fq is None:
                    all_resolved = False
                    continue
                _, cfn = index.functions[fq]
                crec = cfn.get("lifecycle") or {"releases": [], "owns": []}
                off = 1 if callee.startswith("self.") else 0
                resolved.append((callee, crec, pos + off))
            if p["confirm"] == "releases":
                hits = [c for c, crec, idx in resolved
                        if idx in crec["releases"]]
                if hits:
                    out.append(Finding(
                        path=s["path"], line=p["line"], col=p["col"],
                        rule=p["rule"],
                        message=p["message"].replace("{callee}", hits[0])))
            else:  # none_own: every callee must provably not take it
                if not resolved or not all_resolved:
                    continue
                if any(idx in crec["releases"] or idx in crec["owns"]
                       or idx in crec.get("escapes", ())
                       for _, crec, idx in resolved):
                    # a callee that releases/keeps it — or hands it to
                    # someone IT cannot see — is not a proven leak
                    continue
                out.append(Finding(
                    path=s["path"], line=p["line"], col=p["col"],
                    rule=p["rule"],
                    message=p["message"].replace(
                        "{callee}", resolved[0][0])))
    return out


def aggregate_stats(summaries) -> Dict[str, int]:
    total: Dict[str, int] = {}
    for s in summaries:
        for k, v in (s.get("lifecycle") or {}).get("stats", {}).items():
            total[k] = total.get(k, 0) + int(v)
    return total
