"""Baseline support: adopt graftcheck on a tree with pre-existing
findings without blessing new ones.

``--write-baseline FILE`` records every current finding as a
fingerprint; ``--baseline FILE`` then filters findings whose
fingerprint is known. Fingerprints hash (rule, path, stripped source
line text, same-text occurrence index) — NOT the line number — so
unrelated edits above a finding don't resurrect it; moving or editing
the flagged line itself does, which is the desired behavior (the code
changed, re-review it).

The rule id in the key means a GC030 and a GC032 anchored on the same
line never mask each other when only one is baselined. The occurrence
index (position among findings sharing the same rule+path+text,
ordered by line) means two findings on *identical duplicated lines*
(two ``pool.free(b)`` lines, say) get distinct fingerprints too —
baselining one no longer hides the other. Index 0 is omitted from the
key, so single-occurrence fingerprints (the overwhelmingly common
case) are stable across this change.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .local import Finding


def _line_text(path: str, line: int,
               cache: Dict[str, List[str]]) -> str:
    if path not in cache:
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                cache[path] = f.read().splitlines()
        except OSError:
            cache[path] = []
    lines = cache[path]
    return lines[line - 1].strip() if 0 < line <= len(lines) else ""


def _base_key(f: Finding, cache: Dict[str, List[str]]
              ) -> Tuple[str, str, str]:
    return (f.rule, os.path.normpath(f.path),
            _line_text(f.path, f.line, cache))


def _hash(base: Tuple[str, str, str], occurrence: int) -> str:
    key = "\x00".join(base)
    if occurrence:
        key += f"\x00{occurrence}"
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]


def fingerprint(f: Finding, cache: Dict[str, List[str]],
                occurrence: int = 0) -> str:
    return _hash(_base_key(f, cache), occurrence)


def _fingerprints(findings: Sequence[Finding],
                  cache: Dict[str, List[str]]) -> List[str]:
    """One fingerprint per finding, disambiguating same-text repeats by
    their order of appearance (sorted by line, then column)."""
    order = sorted(range(len(findings)),
                   key=lambda i: (findings[i].path, findings[i].line,
                                  findings[i].col, findings[i].rule))
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[str] = [""] * len(findings)
    for i in order:
        base = _base_key(findings[i], cache)
        occ = seen.get(base, 0)
        seen[base] = occ + 1
        out[i] = _hash(base, occ)
    return out


def write(path: str, findings: Sequence[Finding]) -> None:
    cache: Dict[str, List[str]] = {}
    fps = _fingerprints(findings, cache)
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "fingerprint": fp} for f, fp in zip(findings, fps)]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 2, "findings": entries}, fh, indent=2)
        fh.write("\n")


def load(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"] for e in data.get("findings", ())}


def _load_entries(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("findings", ()))


def filter_findings(findings: Sequence[Finding],
                    baseline_path: Optional[str]) -> List[Finding]:
    """Suppress baselined findings. Matching is COUNT-based per
    (rule, path, line text) group: N baseline entries for a group
    suppress N of its current findings. Which N: findings sitting on a
    line the baseline recorded are suppressed first — so a NEW
    identical-text finding appearing ABOVE a baselined one is the one
    reported, not the one silently absorbed into the old entry's
    occurrence-0 fingerprint. Unmatched-line entries (the flagged code
    moved) fall back to line order."""
    if not baseline_path:
        return list(findings)
    entries = _load_entries(baseline_path)
    known: Set[str] = {e["fingerprint"] for e in entries}
    lines_of: Dict[str, Set[int]] = {}
    for e in entries:
        lines_of.setdefault(e["fingerprint"], set()).add(
            int(e.get("line", 0)))
    cache: Dict[str, List[str]] = {}

    groups: Dict[Tuple[str, str, str], List[int]] = {}
    order = sorted(range(len(findings)),
                   key=lambda i: (findings[i].path, findings[i].line,
                                  findings[i].col, findings[i].rule))
    for i in order:
        groups.setdefault(_base_key(findings[i], cache), []).append(i)

    suppressed: Set[int] = set()
    for base, idxs in groups.items():
        # how many entries did the baseline record for this group?
        # (write() assigned contiguous occurrence indices 0..m-1)
        m = 0
        baselined_lines: Set[int] = set()
        while m < len(idxs) + 64:
            fp = _hash(base, m)
            if fp not in known:
                break
            baselined_lines |= lines_of.get(fp, set())
            m += 1
        if m == 0:
            continue
        on_known_line = [i for i in idxs
                         if findings[i].line in baselined_lines]
        take = on_known_line[:m]
        for i in idxs:       # drifted lines: fall back to line order
            if len(take) >= m:
                break
            if i not in take:
                take.append(i)
        suppressed.update(take)
    return [f for i, f in enumerate(findings) if i not in suppressed]
