"""Baseline support: adopt graftcheck on a tree with pre-existing
findings without blessing new ones.

``--write-baseline FILE`` records every current finding as a
fingerprint; ``--baseline FILE`` then filters findings whose
fingerprint is known. Fingerprints hash (rule, path, stripped source
line text) — NOT the line number — so unrelated edits above a finding
don't resurrect it; moving or editing the flagged line itself does,
which is the desired behavior (the code changed, re-review it).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Set

from .local import Finding


def _line_text(path: str, line: int,
               cache: Dict[str, List[str]]) -> str:
    if path not in cache:
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                cache[path] = f.read().splitlines()
        except OSError:
            cache[path] = []
    lines = cache[path]
    return lines[line - 1].strip() if 0 < line <= len(lines) else ""


def fingerprint(f: Finding, cache: Dict[str, List[str]]) -> str:
    text = _line_text(f.path, f.line, cache)
    key = f"{f.rule}\x00{os.path.normpath(f.path)}\x00{text}"
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]


def write(path: str, findings: Sequence[Finding]) -> None:
    cache: Dict[str, List[str]] = {}
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "fingerprint": fingerprint(f, cache)} for f in findings]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2)
        fh.write("\n")


def load(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"] for e in data.get("findings", ())}


def filter_findings(findings: Sequence[Finding],
                    baseline_path: Optional[str]) -> List[Finding]:
    if not baseline_path:
        return list(findings)
    known = load(baseline_path)
    cache: Dict[str, List[str]] = {}
    return [f for f in findings if fingerprint(f, cache) not in known]
