"""StandardAutoscaler — the reconcile loop.

ref: python/ray/autoscaler/_private/autoscaler.py:166 StandardAutoscaler
(update :368: read load -> bin-pack unmet demand -> launch; terminate
idle), resource_demand_scheduler.py for the packing. Single-controller
reduction: demand is read directly off the head runtime — parked task
specs, per-node lease queues, pending placement groups — no gossip hop.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..core.ids import NodeId
from ..core.resources import ResourceSet, normalize, res_ge, res_sub
from .provider import NodeProvider


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    idle_timeout_s: float = 30.0
    update_interval_s: float = 1.0
    # launch at most this many nodes per update pass (ref: upscaling_speed)
    max_launch_batch: int = 2


class StandardAutoscaler:
    def __init__(self, runtime, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self.runtime = runtime
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._last_busy: Dict[NodeId, float] = {}
        self._requested: List[ResourceSet] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "StandardAutoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="autoscaler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                import traceback

                traceback.print_exc()
            self._stop.wait(self.config.update_interval_s)

    # -- explicit demand (ref: ray.autoscaler.sdk.request_resources) ----------

    def request_resources(self, bundles: List[ResourceSet]) -> None:
        """Pin a demand floor independent of queued work. Bundles stay
        separate demands — aggregating them would turn N node-sized
        requests into one unsatisfiable super-node request and the
        launch loop would never fire (ref: sdk.request_resources treats
        each bundle as independently placeable)."""
        with self._lock:
            self._requested = [normalize(b) for b in bundles]

    # -- demand / supply -------------------------------------------------------

    def _pending_demands(self) -> List[ResourceSet]:
        """One entry per schedulable unit that cannot run right now."""
        rt = self.runtime
        demands: List[ResourceSet] = []
        with rt._lock:
            parked = list(rt._parked)
        for spec in parked:
            demands.append(normalize(spec.resources))
        for node in list(rt.nodes.values()):
            if not node.alive:
                continue
            with node._lock:
                # _lease_queue is bucketed by (demand, pg, env) signature
                # since the dispatch rework — walk the buckets, not the
                # keys (iterating the dict yields signature tuples)
                for bucket in node._lease_queue.values():
                    for req in bucket:
                        demands.append(dict(req.demand))
        for pg in rt.gcs.list_pgs():
            if pg.state == "PENDING":
                demands.extend(normalize(b) for b in pg.bundles)
        with self._lock:
            demands.extend(dict(b) for b in self._requested)
        return [d for d in demands if d]

    def _unmet_after_packing(self, demands: List[ResourceSet]) -> int:
        """Greedy first-fit of demands onto current availability; returns
        how many demands no node can absorb (ref:
        resource_demand_scheduler.py bin packing). Draining
        (preemption-noticed) nodes are NOT supply: their capacity is
        already promised to the axe, so replacements launch now."""
        rt = self.runtime
        avail = []
        for node in rt.nodes.values():
            if node.alive and not getattr(node, "draining", False):
                with node._lock:
                    avail.append(dict(node.available))
        unmet = 0
        for d in demands:
            for a in avail:
                if res_ge(a, d):
                    a.update(res_sub(a, d))
                    break
            else:
                unmet += 1
        return unmet

    # -- preemption notices ----------------------------------------------------

    def _is_draining(self, node_id: NodeId) -> bool:
        node = self.runtime.nodes.get(node_id)
        return node is not None and getattr(node, "draining", False)

    def _deliver_preemptions(self) -> int:
        """Pull the provider's preemption notices and turn each into the
        runtime's drain path: ``NODE_PREEMPTING`` GCS event (workloads
        subscribe), scheduler drain filter, serve-replica draining, and
        the agent's clean-exit backstop. Returns notices delivered."""
        try:
            notices = self.provider.poll_preemptions()
        except Exception:
            import traceback

            traceback.print_exc()
            return 0
        delivered = 0
        for node_id, grace_s in notices:
            try:
                self.runtime.on_preemption_notice(
                    node_id, grace_s, reason="provider preemption notice")
                delivered += 1
            except Exception:
                import traceback

                traceback.print_exc()
        return delivered

    # -- one reconcile pass ----------------------------------------------------

    def update(self) -> dict:
        cfg = self.config
        # preemption notices first: a noticed node must stop being
        # supply BEFORE this pass packs demand, so the replacement
        # launches in the same tick the notice arrives
        preempting = self._deliver_preemptions()
        provider_nodes = set(self.provider.non_terminated_nodes())
        active_nodes = {nid for nid in provider_nodes
                        if not self._is_draining(nid)}
        demands = self._pending_demands()
        unmet = self._unmet_after_packing(demands)

        launched = 0
        per_node = self.provider.node_resources()
        # the cap counts only non-draining nodes: a preemption-noticed
        # node is leaving anyway, and its replacement must launch NOW
        # (brief real-node overlap during the grace window is the whole
        # point of drain-before-the-axe)
        while (unmet > 0 and launched < cfg.max_launch_batch
               and len(active_nodes) + launched < cfg.max_workers):
            # each new node absorbs however many unmet demands fit on it
            cap = dict(per_node)
            absorbed = 0
            for d in demands:
                if res_ge(cap, d):
                    cap.update(res_sub(cap, d))
                    absorbed += 1
            if absorbed == 0:
                break  # demand shaped wrong for this node type: stop
            self.provider.create_node()
            launched += 1
            unmet = max(0, unmet - absorbed)
        # min_workers floor counts only non-draining nodes: a noticed
        # node is already promised to the axe, so its replacement
        # launches without waiting for it to actually die
        while len(active_nodes) + launched < cfg.min_workers:
            self.provider.create_node()
            launched += 1

        # idle reclamation: a provider node with no lease activity and no
        # queue for idle_timeout_s gets terminated (never below min_workers)
        now = time.monotonic()
        terminated = []
        idle_terminated = 0  # non-draining reclaims only (floor math)
        provider_nodes = set(self.provider.non_terminated_nodes())
        for nid in list(provider_nodes):
            node = self.runtime.nodes.get(nid)
            if node is None or not node.alive:
                self._last_busy.pop(nid, None)
                if node is not None and not node.alive:
                    # the node died out from under the provider (the
                    # axe beat the drain, a crash): terminate anyway so
                    # the provider prunes its ledger — a TPU slice host
                    # occupied by a corpse can never relaunch otherwise
                    try:
                        self.provider.terminate_node(nid)
                        terminated.append(nid)
                    except Exception:
                        pass
                continue
            with node._lock:
                busy = (bool(node._lease_queue)
                        or any(w.state in ("leased", "actor")
                               for w in node._workers.values()))
            if getattr(node, "draining", False):
                # shrink-before-the-axe: the moment a noticed node has
                # no busy workers left, terminate it CLEANLY — don't
                # gift the platform a SIGKILL target. No idle_timeout,
                # no min_workers guard (the node is doomed either way).
                if not busy:
                    self.provider.terminate_node(nid)
                    terminated.append(nid)
                    self._last_busy.pop(nid, None)
                continue
            if busy:
                self._last_busy[nid] = now
                continue
            # drained terminations never counted toward the active sum,
            # so only idle reclaims of ACTIVE nodes deplete the floor
            active_left = sum(1 for n in provider_nodes
                              if not self._is_draining(n)) - idle_terminated
            if now - self._last_busy.setdefault(nid, now) \
                    > cfg.idle_timeout_s \
                    and active_left > cfg.min_workers:
                self.provider.terminate_node(nid)
                terminated.append(nid)
                idle_terminated += 1
                self._last_busy.pop(nid, None)
        return {"pending_demands": len(demands), "unmet": unmet,
                "launched": launched, "terminated": len(terminated),
                "notices_delivered": preempting,
                "preempting": sum(
                    1 for nid in self.provider.non_terminated_nodes()
                    if self._is_draining(nid)),
                "provider_nodes": len(self.provider.non_terminated_nodes())}
