"""StandardAutoscaler — the reconcile loop.

ref: python/ray/autoscaler/_private/autoscaler.py:166 StandardAutoscaler
(update :368: read load -> bin-pack unmet demand -> launch; terminate
idle), resource_demand_scheduler.py for the packing. Single-controller
reduction: demand is read directly off the head runtime — parked task
specs, per-node lease queues, pending placement groups — no gossip hop.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..core.ids import NodeId
from ..core.resources import ResourceSet, normalize, res_ge, res_sub
from .provider import NodeProvider


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    idle_timeout_s: float = 30.0
    update_interval_s: float = 1.0
    # launch at most this many nodes per update pass (ref: upscaling_speed)
    max_launch_batch: int = 2


class StandardAutoscaler:
    def __init__(self, runtime, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self.runtime = runtime
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._last_busy: Dict[NodeId, float] = {}
        self._requested: List[ResourceSet] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "StandardAutoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="autoscaler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                import traceback

                traceback.print_exc()
            self._stop.wait(self.config.update_interval_s)

    # -- explicit demand (ref: ray.autoscaler.sdk.request_resources) ----------

    def request_resources(self, bundles: List[ResourceSet]) -> None:
        """Pin a demand floor independent of queued work. Bundles stay
        separate demands — aggregating them would turn N node-sized
        requests into one unsatisfiable super-node request and the
        launch loop would never fire (ref: sdk.request_resources treats
        each bundle as independently placeable)."""
        with self._lock:
            self._requested = [normalize(b) for b in bundles]

    # -- demand / supply -------------------------------------------------------

    def _pending_demands(self) -> List[ResourceSet]:
        """One entry per schedulable unit that cannot run right now."""
        rt = self.runtime
        demands: List[ResourceSet] = []
        with rt._lock:
            parked = list(rt._parked)
        for spec in parked:
            demands.append(normalize(spec.resources))
        for node in list(rt.nodes.values()):
            if not node.alive:
                continue
            with node._lock:
                for req in list(node._lease_queue):
                    demands.append(dict(req.demand))
        for pg in rt.gcs.list_pgs():
            if pg.state == "PENDING":
                demands.extend(normalize(b) for b in pg.bundles)
        with self._lock:
            demands.extend(dict(b) for b in self._requested)
        return [d for d in demands if d]

    def _unmet_after_packing(self, demands: List[ResourceSet]) -> int:
        """Greedy first-fit of demands onto current availability; returns
        how many demands no node can absorb (ref:
        resource_demand_scheduler.py bin packing)."""
        rt = self.runtime
        avail = []
        for node in rt.nodes.values():
            if node.alive:
                with node._lock:
                    avail.append(dict(node.available))
        unmet = 0
        for d in demands:
            for a in avail:
                if res_ge(a, d):
                    a.update(res_sub(a, d))
                    break
            else:
                unmet += 1
        return unmet

    # -- one reconcile pass ----------------------------------------------------

    def update(self) -> dict:
        cfg = self.config
        provider_nodes = set(self.provider.non_terminated_nodes())
        demands = self._pending_demands()
        unmet = self._unmet_after_packing(demands)

        launched = 0
        per_node = self.provider.node_resources()
        while (unmet > 0 and launched < cfg.max_launch_batch
               and len(provider_nodes) + launched < cfg.max_workers):
            # each new node absorbs however many unmet demands fit on it
            cap = dict(per_node)
            absorbed = 0
            for d in demands:
                if res_ge(cap, d):
                    cap.update(res_sub(cap, d))
                    absorbed += 1
            if absorbed == 0:
                break  # demand shaped wrong for this node type: stop
            self.provider.create_node()
            launched += 1
            unmet = max(0, unmet - absorbed)
        while len(provider_nodes) + launched < cfg.min_workers:
            self.provider.create_node()
            launched += 1

        # idle reclamation: a provider node with no lease activity and no
        # queue for idle_timeout_s gets terminated (never below min_workers)
        now = time.monotonic()
        terminated = []
        provider_nodes = set(self.provider.non_terminated_nodes())
        for nid in list(provider_nodes):
            node = self.runtime.nodes.get(nid)
            if node is None or not node.alive:
                self._last_busy.pop(nid, None)
                continue
            with node._lock:
                busy = (bool(node._lease_queue)
                        or any(w.state in ("leased", "actor")
                               for w in node._workers.values()))
            if busy:
                self._last_busy[nid] = now
                continue
            if now - self._last_busy.setdefault(nid, now) \
                    > cfg.idle_timeout_s \
                    and len(provider_nodes) - len(terminated) \
                    > cfg.min_workers:
                self.provider.terminate_node(nid)
                terminated.append(nid)
                self._last_busy.pop(nid, None)
        return {"pending_demands": len(demands), "unmet": unmet,
                "launched": launched, "terminated": len(terminated),
                "provider_nodes": len(self.provider.non_terminated_nodes())}
