"""Cluster launcher — `ray_tpu up / down / attach / exec` from a YAML
cluster config.

ref: python/ray/autoscaler/_private/commands.py (create_or_update_cluster
:690, teardown_cluster, attach_cluster, exec_cluster) and updater.py (the
SSH NodeUpdater: file mounts -> setup commands -> start command). The
structure here is the same three layers:

- `CommandRunner`: how to reach a node. `LocalCommandRunner` (subprocess
  on this host — the testable path, like the reference's fake_multi_node)
  and `SSHCommandRunner` (ssh/scp subprocess; BatchMode, connection
  timeouts, no external deps).
- `NodeUpdater`: bootstrap one node — push file mounts, run setup
  commands, run the start command.
- `cluster_up/down/attach/exec`: orchestration + a state file under
  ~/.ray_tpu/clusters/<name>.json recording the head address, auth key,
  and launched nodes so later commands can find the cluster.

Config schema (YAML):

    cluster_name: demo
    provider:
      type: local            # or: ssh
      worker_ips: [a, b]     # ssh only
      ssh_user: ubuntu       # ssh only
      ssh_key: ~/.ssh/id     # ssh only
      head_ip: 10.0.0.1      # ssh only (where the head runs)
    head:
      port: 6380
      num_cpus: 4
      resources: {}
    workers:
      count: 2
      num_cpus: 2
      resources: {}
    file_mounts: {/remote/path: /local/path}   # ssh only
    setup_commands: ["pip list"]                # run before start
"""
from __future__ import annotations

import json
import os
import secrets
import shlex
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..util.retry import RetryPolicy, call_with_retry

STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


# ---------------------------------------------------------------------------
# command runners (ref: autoscaler/_private/command_runner.py)
# ---------------------------------------------------------------------------


class CommandRunner:
    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            background: bool = False) -> subprocess.Popen:
        raise NotImplementedError

    def check(self, cmd: str, env: Optional[Dict[str, str]] = None,
              timeout: float = 120.0) -> str:
        raise NotImplementedError

    def put(self, local: str, remote: str) -> None:
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Nodes are processes on this host (the fake_multi_node analog)."""

    def run(self, cmd, env=None, background=False):
        full_env = {**os.environ, **(env or {})}
        return subprocess.Popen(cmd, shell=True, env=full_env,
                                start_new_session=background)

    def check(self, cmd, env=None, timeout=120.0):
        full_env = {**os.environ, **(env or {})}
        out = subprocess.run(cmd, shell=True, env=full_env, timeout=timeout,
                             capture_output=True, text=True)
        if out.returncode != 0:
            raise RuntimeError(
                f"command failed rc={out.returncode}: {cmd}\n{out.stderr}")
        return out.stdout

    def put(self, local, remote):
        import shutil

        if os.path.abspath(local) == os.path.abspath(remote):
            return
        os.makedirs(os.path.dirname(remote), exist_ok=True)
        if os.path.isdir(local):
            shutil.copytree(local, remote, dirs_exist_ok=True)
        else:
            shutil.copy2(local, remote)


class SSHCommandRunner(CommandRunner):
    """Reach a node over ssh/scp subprocesses (ref: command_runner.py
    SSHCommandRunner; BatchMode so a missing key fails fast instead of
    prompting)."""

    # transport-level retries (util/retry.py, the GC012-clean shape):
    # ssh exits 255 when the CONNECTION failed — the remote command never
    # ran, so retrying is safe; scp is idempotent (full re-copy). Nodes
    # routinely answer a beat after boot, so a couple of backed-off
    # attempts is the difference between `up` working first try and not.
    _TRANSPORT_RETRY = RetryPolicy(initial_backoff_s=0.5, multiplier=2.0,
                                   max_backoff_s=4.0, max_attempts=4)

    def __init__(self, host: str, user: str = "", key: str = ""):
        self.host = host
        self.user = user
        self.key = key

    def _ssh_base(self) -> List[str]:
        base = ["ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=15",
                "-o", "StrictHostKeyChecking=accept-new"]
        if self.key:
            base += ["-i", os.path.expanduser(self.key)]
        target = f"{self.user}@{self.host}" if self.user else self.host
        return base + [target]

    log_path = "~/.ray_tpu/launch.log"  # set per node by the launcher

    def run(self, cmd, env=None, background=False):
        envs = " ".join(f"{k}={shlex.quote(v)}" for k, v in (env or {}).items())
        log = shlex.quote(self.log_path)
        remote = (f"mkdir -p ~/.ray_tpu && {envs} nohup {cmd} "
                  f">{log} 2>&1 &") if background else f"{envs} {cmd}"
        return subprocess.Popen(self._ssh_base() + [remote])

    class _SSHConnectError(RuntimeError):
        """ssh rc=255 with client-side transport diagnostics: the
        connection failed, the remote command never ran — the only
        failure class check() retries."""

    @staticmethod
    def _is_transport_error(stderr: str) -> bool:
        s = (stderr or "").lower()
        return any(m in s for m in (
            "ssh:", "connection refused", "connection timed out",
            "connection reset", "connection closed",
            "no route to host", "could not resolve",
            "operation timed out", "kex_exchange", "broken pipe"))

    def check(self, cmd, env=None, timeout=120.0):
        envs = " ".join(f"{k}={shlex.quote(v)}" for k, v in (env or {}).items())

        def _once():
            out = subprocess.run(self._ssh_base() + [f"{envs} {cmd}"],
                                 timeout=timeout, capture_output=True,
                                 text=True)
            if out.returncode == 255 and self._is_transport_error(
                    out.stderr):
                # rc=255 ALONE is ambiguous (a remote command may itself
                # exit 255); only the ssh client's own transport
                # diagnostics make a retry safe — the command never ran
                raise self._SSHConnectError(
                    f"ssh {self.host} unreachable: {out.stderr}")
            if out.returncode != 0:
                raise RuntimeError(
                    f"ssh {self.host} failed rc={out.returncode}: {cmd}\n"
                    f"{out.stderr}")
            return out.stdout

        return call_with_retry(_once, policy=self._TRANSPORT_RETRY,
                               retry_on=(self._SSHConnectError,),
                               description=f"ssh {self.host}")

    def put(self, local, remote):
        target = f"{self.user}@{self.host}" if self.user else self.host
        scp = ["scp", "-o", "BatchMode=yes", "-r"]
        if self.key:
            scp += ["-i", os.path.expanduser(self.key)]
        call_with_retry(
            lambda: subprocess.run(scp + [local, f"{target}:{remote}"],
                                   check=True, timeout=300),
            policy=self._TRANSPORT_RETRY,
            retry_on=(subprocess.CalledProcessError,),
            description=f"scp {local} -> {self.host}")


# ---------------------------------------------------------------------------
# node bootstrap (ref: autoscaler/_private/updater.py NodeUpdater.run)
# ---------------------------------------------------------------------------


class NodeUpdater:
    def __init__(self, runner: CommandRunner, config: dict,
                 env: Dict[str, str]):
        self.runner = runner
        self.config = config
        self.env = env

    def bootstrap(self, start_cmd: str) -> subprocess.Popen:
        for remote, local in (self.config.get("file_mounts") or {}).items():
            self.runner.put(os.path.expanduser(local),
                            os.path.expanduser(remote))
        for cmd in self.config.get("setup_commands") or []:
            self.runner.check(cmd, env=self.env)
        return self.runner.run(start_cmd, env=self.env, background=True)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------


def _load_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    # `head:` with no children parses as None — normalize falsy sections
    cfg["provider"] = cfg.get("provider") or {"type": "local"}
    cfg["head"] = cfg.get("head") or {}
    cfg["workers"] = cfg.get("workers") or {}
    if not cfg.get("cluster_name"):
        raise ValueError(f"{path}: cluster_name is required")
    return cfg


def _state_path(name: str) -> str:
    os.makedirs(STATE_DIR, exist_ok=True)
    return os.path.join(STATE_DIR, f"{name}.json")


def _save_state(name: str, state: dict) -> None:
    path = _state_path(name)
    with open(path, "w") as f:
        json.dump(state, f, indent=2)
    os.chmod(path, 0o600)  # it holds the cluster auth token


def load_state(name: str) -> dict:
    with open(_state_path(name)) as f:
        return json.load(f)


def _runner_for(provider: dict, host: Optional[str]) -> CommandRunner:
    if provider.get("type", "local") == "local":
        return LocalCommandRunner()
    return SSHCommandRunner(host, provider.get("ssh_user", ""),
                            provider.get("ssh_key", ""))


def _python() -> str:
    return shlex.quote(sys.executable)


def cluster_up(config_path: str, wait_workers_s: float = 60.0) -> dict:
    """Start the head, then bootstrap every worker node with the join
    command. Returns the cluster state dict (also persisted)."""
    cfg = _load_config(config_path)
    name = cfg["cluster_name"]
    provider = cfg["provider"]
    head_cfg = cfg["head"]
    authkey = secrets.token_bytes(32).hex()
    # a NON-secret nonce rides in every node's argv so teardown can
    # pkill by it; the authkey itself travels env-only (argv is visible
    # to every local user via /proc)
    nonce = f"rtpu-{name}-{secrets.token_hex(8)}"
    host = head_cfg.get("host", "127.0.0.1"
                        if provider.get("type", "local") == "local"
                        else "0.0.0.0")
    port = int(head_cfg.get("port", 6380))
    env = {"RTPU_AUTHKEY": authkey}
    if provider.get("type", "local") == "local":
        # local nodes resolve ray_tpu from this checkout; remote hosts
        # have their own install — exporting our sys.path there would
        # shadow theirs with wrong-or-stale paths
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)

    workers_cfg = cfg["workers"]
    count = int(workers_cfg.get("count", 0))
    worker_ips = provider.get("worker_ips") or []
    if provider.get("type", "local") != "local":
        if count > len(worker_ips):
            raise ValueError(
                f"workers.count={count} but provider.worker_ips has only "
                f"{len(worker_ips)} hosts")
        if not provider.get("head_ip"):
            raise ValueError(
                "ssh provider needs head_ip (where the head process runs)")

    head_runner = _runner_for(provider, provider.get("head_ip"))
    if isinstance(head_runner, SSHCommandRunner):
        head_runner.log_path = f"~/.ray_tpu/launch_{name}_head.log"
    head_cmd = (f"{_python()} -m ray_tpu start --head --host {host} "
                f"--port {port} --num-cpus {head_cfg.get('num_cpus', 4)} "
                f"--resources {shlex.quote(json.dumps(head_cfg.get('resources') or {}))} "
                f"--cluster-name {nonce}")
    head_proc = NodeUpdater(head_runner, cfg, env).bootstrap(head_cmd)
    join_host = provider.get("head_ip", "127.0.0.1")
    address = f"{join_host}:{port}"
    # state is persisted as soon as anything is running: a failure later
    # in bring-up must still leave `ray_tpu down <name>` able to find and
    # kill what was launched
    state = {"cluster_name": name, "address": address, "authkey": authkey,
             "nonce": nonce,
             "head_pid": getattr(head_proc, "pid", None),
             "worker_pids": [], "provider": provider,
             "config_path": os.path.abspath(config_path),
             "started_at": time.time()}
    _save_state(name, state)
    try:
        _wait_port(join_host if join_host != "0.0.0.0" else "127.0.0.1",
                   port, timeout=30)
        for i in range(count):
            w_host = worker_ips[i] if i < len(worker_ips) else None
            runner = _runner_for(provider, w_host)
            if isinstance(runner, SSHCommandRunner):
                runner.log_path = f"~/.ray_tpu/launch_{name}_worker{i}.log"
            join_cmd = (
                f"{_python()} -m ray_tpu start --address {address} "
                f"--num-cpus {workers_cfg.get('num_cpus', 2)} "
                f"--resources {shlex.quote(json.dumps(workers_cfg.get('resources') or {}))} "
                f"--cluster-name {nonce}")
            proc = NodeUpdater(runner, cfg, env).bootstrap(join_cmd)
            state["worker_pids"].append(getattr(proc, "pid", None))
            _save_state(name, state)
        if count:
            _wait_workers(address, authkey, count, wait_workers_s)
    except BaseException:
        _save_state(name, state)  # whatever launched is on record
        raise
    return state


# bring-up polls (util/retry.py): fixed-cadence attempts under a hard
# deadline — the launcher's old hand-rolled while/sleep loops, now on
# the shared policy so GC012 has one shape to bless
_PORT_WAIT = RetryPolicy(initial_backoff_s=0.2, multiplier=1.0,
                         max_backoff_s=0.2, jitter=0.0)
_WORKER_WAIT = RetryPolicy(initial_backoff_s=0.5, multiplier=1.0,
                           max_backoff_s=0.5, jitter=0.0)


def _wait_port(host: str, port: int, timeout: float) -> None:
    import socket

    deadline = time.monotonic() + timeout
    for _attempt in _PORT_WAIT.sleeps(deadline=deadline):
        try:
            with socket.create_connection((host, port), timeout=1):
                return
        except OSError:
            continue
    raise TimeoutError(f"head {host}:{port} did not come up in {timeout}s")


def _wait_workers(address: str, authkey: str, count: int,
                  timeout: float) -> None:
    """Poll the head's node table until all workers joined."""
    deadline = time.monotonic() + timeout
    for _attempt in _WORKER_WAIT.sleeps(deadline=deadline):
        try:
            if len(_alive_nodes(address, authkey)) >= count + 1:
                return
        except Exception:
            continue
    raise TimeoutError(f"{count} workers did not join within {timeout}s")


def _alive_nodes(address: str, authkey: str) -> list:
    from ..core.rpc import connect

    host, _, port = address.rpartition(":")
    # authkey passed explicitly: cluster_token() caches per-process, and
    # a launcher driving a brand-new cluster from a process that already
    # belonged to another one must not reuse the stale token
    ch = connect((host, int(port)), authkey=bytes.fromhex(authkey),
                 name="launcher")
    try:
        return [n for n in ch.call("list_nodes", None, timeout=15)
                if n.get("alive")]
    finally:
        ch.close()


def cluster_down(name_or_config: str) -> None:
    """Terminate every node of the cluster (ref: commands.py
    teardown_cluster)."""
    name = name_or_config
    if name.endswith((".yaml", ".yml", ".json")):
        name = _load_config(name_or_config)["cluster_name"]
    state = load_state(name)
    provider = state.get("provider") or {"type": "local"}
    if provider.get("type", "local") == "local":
        needle = state.get("nonce") or "ray_tpu"
        for pid in [*state.get("worker_pids", []), state.get("head_pid")]:
            if pid and _pid_matches(int(pid), needle):
                _kill_tree(int(pid))
    else:
        # scope the kill to THIS cluster: every launched process carries
        # the cluster's non-secret nonce in argv, so matching it cannot
        # touch other clusters (or hand-started nodes) sharing the host.
        # NEVER fall back to the authkey — pkill -f would place the
        # secret in remote argv (/proc, shell history on shared hosts)
        nonce = state.get("nonce")
        if not nonce:
            raise RuntimeError(
                f"cluster state for {name!r} predates nonce tracking; "
                "refusing a pattern kill that would expose the authkey. "
                "Kill the recorded pids by hand "
                f"(head={state.get('head_pid')} "
                f"workers={state.get('worker_pids', [])}), then delete "
                f"{_state_path(name)} to finish the teardown.")
        pat = shlex.quote(nonce)
        for ip in (provider.get("worker_ips") or []) + \
                [provider.get("head_ip")]:
            if not ip:
                continue
            try:
                SSHCommandRunner(ip, provider.get("ssh_user", ""),
                                 provider.get("ssh_key", "")).check(
                    f"pkill -f {pat} || true", timeout=30)
            except Exception:
                pass
    try:
        os.remove(_state_path(name))
    except FileNotFoundError:
        pass


def _pid_matches(pid: int, needle: str) -> bool:
    """Stale state files survive reboots and pid recycling: only signal a
    process whose cmdline still carries this cluster's nonce."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return needle.encode() in f.read()
    except FileNotFoundError:
        return False
    except OSError:
        return True  # no /proc (non-Linux): keep the old behavior


def _kill_tree(pid: int) -> None:
    """The launcher started nodes with start_new_session=True, so the
    process group id is the child's pid — signal the whole group (worker
    subprocesses included), then reap if it was our own child (a killed
    but unreaped child is a zombie that still answers os.kill(pid, 0))."""
    for sig in (signal.SIGTERM, signal.SIGKILL):
        try:
            os.killpg(pid, sig)
        except ProcessLookupError:
            break
        except PermissionError:
            os.kill(pid, sig)
        time.sleep(0.3)
    try:
        for _ in range(20):
            done, _status = os.waitpid(pid, os.WNOHANG)
            if done:
                break
            time.sleep(0.05)
    except (ChildProcessError, OSError):
        pass  # not our child: init reaps it


def exec_on_head(name_or_config: str, cmd: str, timeout: float = 300.0) -> str:
    """Run a shell command on the head node with the cluster's auth env
    (ref: commands.py exec_cluster)."""
    name = name_or_config
    if name.endswith((".yaml", ".yml", ".json")):
        name = _load_config(name_or_config)["cluster_name"]
    state = load_state(name)
    provider = state.get("provider") or {"type": "local"}
    runner = _runner_for(provider, provider.get("head_ip"))
    env = {"RTPU_AUTHKEY": state["authkey"],
           "RTPU_ADDRESS": state["address"]}
    return runner.check(cmd, env=env, timeout=timeout)


def attach_cmd(name_or_config: str) -> tuple:
    """-> (argv, extra_env) opening an interactive shell on the head with
    the cluster's RTPU_ADDRESS/RTPU_AUTHKEY set, so driver scripts and
    `ray_tpu ... --address $RTPU_ADDRESS` work out of the box (`ray_tpu
    attach` executes it; returned for testability)."""
    name = name_or_config
    if name.endswith((".yaml", ".yml", ".json")):
        name = _load_config(name_or_config)["cluster_name"]
    state = load_state(name)
    provider = state.get("provider") or {"type": "local"}
    env = {"RTPU_ADDRESS": state["address"],
           "RTPU_AUTHKEY": state["authkey"]}
    if provider.get("type", "local") == "local":
        return [os.environ.get("SHELL", "/bin/sh")], env
    host = provider.get("head_ip")
    user = provider.get("ssh_user", "")
    target = f"{user}@{host}" if user else host
    base = ["ssh", "-t"]
    if provider.get("ssh_key"):
        base += ["-i", os.path.expanduser(provider["ssh_key"])]
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    return base + [target, f"env {exports} $SHELL -l"], env
