"""ray_tpu.autoscaler — demand-driven cluster scaling.

Equivalent of the reference's autoscaler v1 (ref:
python/ray/autoscaler/_private/autoscaler.py:166 StandardAutoscaler,
update loop :368, driven by monitor.py:126; testable fake provider:
autoscaler/_private/fake_multi_node/node_provider.py). The TPU-native
unit of scaling is a SLICE (a whole node_agent joining with its chips),
not a VM: providers launch/terminate agents, the reconcile loop reads
demand straight off the head's single-controller state — parked tasks,
queued leases, and pending placement groups.
"""
from .autoscaler import AutoscalerConfig, StandardAutoscaler
from .provider import FakeSliceProvider, NodeProvider, TPUSliceProvider

__all__ = [
    "AutoscalerConfig", "FakeSliceProvider", "NodeProvider",
    "StandardAutoscaler", "TPUSliceProvider",
]
