"""Node providers — how the autoscaler actually adds/removes capacity.

ref: python/ray/autoscaler/node_provider.py NodeProvider interface;
_private/fake_multi_node/node_provider.py FakeMultiNodeProvider (spawns
real local raylets for tests — here: real node_agent processes).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.ids import NodeId
from ..util.retry import RetryPolicy


class NodeProvider:
    """Launch/terminate slice agents. Implementations must be idempotent:
    the reconcile loop may retry either direction after failures."""

    def create_node(self) -> NodeId:
        raise NotImplementedError

    def terminate_node(self, node_id: NodeId) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeId]:
        raise NotImplementedError

    def node_resources(self) -> Dict[str, float]:
        """Resources one launched node contributes (for demand planning)."""
        raise NotImplementedError

    def poll_preemptions(self) -> List[Tuple[NodeId, float]]:
        """Preemption notices since the last poll: ``(node_id,
        grace_s)`` pairs meaning the platform kills that node in
        ``grace_s`` seconds. Each notice is delivered AT MOST ONCE —
        the autoscaler's reconcile pass turns it into a
        ``NODE_PREEMPTING`` GCS event and starts the drain
        (docs/FAULT_TOLERANCE.md "Elasticity")."""
        return []


class FakeSliceProvider(NodeProvider):
    """Spawns local `ray_tpu.core.node_agent` processes as fake slices —
    scale-up/down logic runs for real in CI without cloud credentials
    (ref: fake_multi_node/node_provider.py)."""

    # join-wait poll cadence (util/retry.py): fixed fast polls with a
    # hard deadline rather than a hand-rolled while/sleep loop
    _JOIN_WAIT = RetryPolicy(initial_backoff_s=0.05, multiplier=1.0,
                             max_backoff_s=0.05, jitter=0.0,
                             deadline_s=30.0)

    def __init__(self, runtime, resources_per_node: Optional[Dict] = None):
        self.runtime = runtime
        self._resources = dict(resources_per_node or {"CPU": 2.0})
        self._procs: Dict[NodeId, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._addr = runtime.enable_remote_nodes()
        # scheduled preemptions: node_id -> (notice_at, grace_s,
        # delivered) — the fake platform's maintenance calendar
        self._preempt_sched: Dict[NodeId, list] = {}

    def node_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def create_node(self) -> NodeId:
        node_id = NodeId.from_random()
        res = dict(self._resources)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "ray_tpu.core.node_agent",
             "--address", f"{self._addr[0]}:{self._addr[1]}",
             "--num-cpus", str(res.pop("CPU", 1.0)),
             "--resources", json.dumps(res),
             "--labels", json.dumps({"autoscaled": "1"}),
             "--node-id", node_id.hex()],
            env=env)
        with self._lock:
            self._procs[node_id] = proc
        for _attempt in self._JOIN_WAIT.sleeps():
            node = self.runtime.nodes.get(node_id)
            if node is not None:
                # chaos preempt schedules / Cluster.remove_node reach the
                # agent process through the node handle
                node._agent_proc = proc
                return node_id
            if proc.poll() is not None:
                with self._lock:
                    self._procs.pop(node_id, None)
                raise RuntimeError(
                    f"fake slice agent exited rc={proc.returncode}")
        proc.kill()
        with self._lock:
            self._procs.pop(node_id, None)
        raise TimeoutError("fake slice agent did not join")

    # -- the fake platform's maintenance calendar --------------------------

    def schedule_preemption(self, node_id: NodeId, notice_in_s: float = 0.0,
                            grace_s: float = 10.0) -> None:
        """Arm a scheduled preemption: the notice becomes visible to
        ``poll_preemptions()`` at ``now + notice_in_s``, and the AXE —
        an unconditional SIGKILL of the agent process, exactly what a
        spot platform does — falls at ``notice + grace_s`` whether or
        not anyone drained. A node that exited cleanly first makes the
        kill a no-op."""
        now = time.monotonic()
        with self._lock:
            self._preempt_sched[node_id] = [now + notice_in_s,
                                            float(grace_s), False]

        def _axe():
            time.sleep(max(0.0, notice_in_s + grace_s))
            with self._lock:
                proc = self._procs.get(node_id)
            if proc is not None and proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGKILL)
                except OSError:
                    pass

        threading.Thread(target=_axe, daemon=True,
                         name=f"fake-axe-{node_id.hex()[:8]}").start()

    def poll_preemptions(self) -> List[Tuple[NodeId, float]]:
        now = time.monotonic()
        due = []
        with self._lock:
            for nid, sched in self._preempt_sched.items():
                notice_at, grace, delivered = sched
                if not delivered and now >= notice_at:
                    sched[2] = True
                    due.append((nid, grace))
        return due

    def terminate_node(self, node_id: NodeId) -> None:
        node = self.runtime.nodes.get(node_id)
        if node is not None and node.alive:
            self.runtime._count_preempt_outcome(node)
            node.shutdown()
            self.runtime.on_remote_node_lost(node_id)
        with self._lock:
            self._preempt_sched.pop(node_id, None)
            proc = self._procs.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass

    def non_terminated_nodes(self) -> List[NodeId]:
        with self._lock:
            return [nid for nid, p in self._procs.items()
                    if p.poll() is None]

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class TPUSliceProvider(NodeProvider):
    """TPU-VM slice autodiscovery behind the same interface.

    A multi-host TPU slice pre-provisions its workers: the GCE metadata
    server / env expose the peer hostnames (TPU_WORKER_HOSTNAMES, worker
    id in TPU_WORKER_ID — the same discovery jax.distributed uses). So
    "create" here means STARTING an agent on the next not-yet-joined
    slice worker over the admin channel configured by `launcher` —
    actual VM creation belongs to the platform (GKE/queued resources),
    exactly as the reference delegates VM lifecycle to cloud providers.
    """

    # GCE metadata-server preemption surface (the shape jax.distributed
    # and the reference's TPU pod-manager poll): `maintenance-event`
    # flips to TERMINATE_ON_HOST_MAINTENANCE and `preempted` to TRUE
    # shortly before a spot slice is reclaimed. Env override for tests /
    # non-GCE platforms that mimic the shape.
    METADATA_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                    "instance/maintenance-event")
    PREEMPT_VALUES = ("TERMINATE_ON_HOST_MAINTENANCE", "TRUE", "PREEMPTED")

    def __init__(self, runtime, launcher=None,
                 resources_per_node: Optional[Dict] = None,
                 preempt_grace_s: float = 60.0):
        self.runtime = runtime
        self.launcher = launcher  # callable(hostname, join_addr) -> NodeId
        self._resources = dict(resources_per_node or {"CPU": 1.0, "TPU": 4})
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        self._hosts: List[str] = [h for h in hosts.split(",") if h]
        self._launched: Dict[str, NodeId] = {}
        self._lock = threading.Lock()
        self.preempt_grace_s = float(preempt_grace_s)
        self._preempt_delivered = False

    def discovered_hosts(self) -> List[str]:
        return list(self._hosts)

    def node_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def create_node(self) -> NodeId:
        with self._lock:
            pending = [h for h in self._hosts if h not in self._launched]
        if not pending:
            raise RuntimeError(
                "TPU slice exhausted: all discovered workers joined "
                f"({len(self._hosts)} hosts); provision a larger slice")
        if self.launcher is None:
            raise RuntimeError(
                "TPUSliceProvider needs a launcher callable "
                "(hostname, join_addr) -> NodeId; on GKE this is the pod "
                "exec hook, on TPU-VMs an ssh runner")
        host = pending[0]
        addr = self.runtime.enable_remote_nodes()
        node_id = self.launcher(host, addr)
        with self._lock:
            self._launched[host] = node_id
        return node_id

    def terminate_node(self, node_id: NodeId) -> None:
        node = self.runtime.nodes.get(node_id)
        if node is not None and node.alive:
            node.shutdown()
            self.runtime.on_remote_node_lost(node_id)
        with self._lock:
            for h, nid in list(self._launched.items()):
                if nid == node_id:
                    self._launched.pop(h)

    def non_terminated_nodes(self) -> List[NodeId]:
        with self._lock:
            return list(self._launched.values())

    def _metadata_value(self) -> Optional[str]:
        """One metadata poll; None on any failure (not on GCE, server
        slow, ...) — preemption polling must never wedge the reconcile
        loop. ``RTPU_TPU_METADATA_URL`` overrides the endpoint (tests,
        or platforms that mimic the GCE shape behind a local agent)."""
        import urllib.request

        url = os.environ.get("RTPU_TPU_METADATA_URL") or self.METADATA_URL
        try:
            req = urllib.request.Request(
                url, headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=1.0) as resp:
                return resp.read().decode("utf-8", "replace").strip()
        except Exception:
            return None

    def poll_preemptions(self) -> List[Tuple[NodeId, float]]:
        """A TPU slice is one scheduling unit: a maintenance event on the
        metadata server means the WHOLE slice goes away — every launched
        node gets the notice, once per event. The latch RE-ARMS when the
        metadata value clears (event over, slice relaunched), so the
        next maintenance event months later still delivers."""
        value = self._metadata_value()
        preempting = (value is not None
                      and value.upper() in self.PREEMPT_VALUES)
        if not preempting:
            if value is not None:
                self._preempt_delivered = False  # event cleared: re-arm
            return []
        if self._preempt_delivered:
            return []
        self._preempt_delivered = True
        with self._lock:
            nodes = list(self._launched.values())
        return [(nid, self.preempt_grace_s) for nid in nodes]
