"""Node providers — how the autoscaler actually adds/removes capacity.

ref: python/ray/autoscaler/node_provider.py NodeProvider interface;
_private/fake_multi_node/node_provider.py FakeMultiNodeProvider (spawns
real local raylets for tests — here: real node_agent processes).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..core.ids import NodeId


class NodeProvider:
    """Launch/terminate slice agents. Implementations must be idempotent:
    the reconcile loop may retry either direction after failures."""

    def create_node(self) -> NodeId:
        raise NotImplementedError

    def terminate_node(self, node_id: NodeId) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeId]:
        raise NotImplementedError

    def node_resources(self) -> Dict[str, float]:
        """Resources one launched node contributes (for demand planning)."""
        raise NotImplementedError


class FakeSliceProvider(NodeProvider):
    """Spawns local `ray_tpu.core.node_agent` processes as fake slices —
    scale-up/down logic runs for real in CI without cloud credentials
    (ref: fake_multi_node/node_provider.py)."""

    def __init__(self, runtime, resources_per_node: Optional[Dict] = None):
        self.runtime = runtime
        self._resources = dict(resources_per_node or {"CPU": 2.0})
        self._procs: Dict[NodeId, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._addr = runtime.enable_remote_nodes()

    def node_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def create_node(self) -> NodeId:
        node_id = NodeId.from_random()
        res = dict(self._resources)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "ray_tpu.core.node_agent",
             "--address", f"{self._addr[0]}:{self._addr[1]}",
             "--num-cpus", str(res.pop("CPU", 1.0)),
             "--resources", json.dumps(res),
             "--labels", json.dumps({"autoscaled": "1"}),
             "--node-id", node_id.hex()],
            env=env)
        with self._lock:
            self._procs[node_id] = proc
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if node_id in self.runtime.nodes:
                return node_id
            if proc.poll() is not None:
                with self._lock:
                    self._procs.pop(node_id, None)
                raise RuntimeError(
                    f"fake slice agent exited rc={proc.returncode}")
            time.sleep(0.05)
        proc.kill()
        with self._lock:
            self._procs.pop(node_id, None)
        raise TimeoutError("fake slice agent did not join")

    def terminate_node(self, node_id: NodeId) -> None:
        node = self.runtime.nodes.get(node_id)
        if node is not None and node.alive:
            node.shutdown()
            self.runtime.on_remote_node_lost(node_id)
        with self._lock:
            proc = self._procs.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass

    def non_terminated_nodes(self) -> List[NodeId]:
        with self._lock:
            return [nid for nid, p in self._procs.items()
                    if p.poll() is None]

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class TPUSliceProvider(NodeProvider):
    """TPU-VM slice autodiscovery behind the same interface.

    A multi-host TPU slice pre-provisions its workers: the GCE metadata
    server / env expose the peer hostnames (TPU_WORKER_HOSTNAMES, worker
    id in TPU_WORKER_ID — the same discovery jax.distributed uses). So
    "create" here means STARTING an agent on the next not-yet-joined
    slice worker over the admin channel configured by `launcher` —
    actual VM creation belongs to the platform (GKE/queued resources),
    exactly as the reference delegates VM lifecycle to cloud providers.
    """

    def __init__(self, runtime, launcher=None,
                 resources_per_node: Optional[Dict] = None):
        self.runtime = runtime
        self.launcher = launcher  # callable(hostname, join_addr) -> NodeId
        self._resources = dict(resources_per_node or {"CPU": 1.0, "TPU": 4})
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        self._hosts: List[str] = [h for h in hosts.split(",") if h]
        self._launched: Dict[str, NodeId] = {}
        self._lock = threading.Lock()

    def discovered_hosts(self) -> List[str]:
        return list(self._hosts)

    def node_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def create_node(self) -> NodeId:
        with self._lock:
            pending = [h for h in self._hosts if h not in self._launched]
        if not pending:
            raise RuntimeError(
                "TPU slice exhausted: all discovered workers joined "
                f"({len(self._hosts)} hosts); provision a larger slice")
        if self.launcher is None:
            raise RuntimeError(
                "TPUSliceProvider needs a launcher callable "
                "(hostname, join_addr) -> NodeId; on GKE this is the pod "
                "exec hook, on TPU-VMs an ssh runner")
        host = pending[0]
        addr = self.runtime.enable_remote_nodes()
        node_id = self.launcher(host, addr)
        with self._lock:
            self._launched[host] = node_id
        return node_id

    def terminate_node(self, node_id: NodeId) -> None:
        node = self.runtime.nodes.get(node_id)
        if node is not None and node.alive:
            node.shutdown()
            self.runtime.on_remote_node_lost(node_id)
        with self._lock:
            for h, nid in list(self._launched.items()):
                if nid == node_id:
                    self._launched.pop(h)

    def non_terminated_nodes(self) -> List[NodeId]:
        with self._lock:
            return list(self._launched.values())
