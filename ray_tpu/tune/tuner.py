"""Tuner + TuneController — the experiment engine.

Parity with the reference (ref: python/ray/tune/tuner.py:320 Tuner.fit;
tune/execution/tune_controller.py:49, event loop `step`:267 — trials run
as actors inside per-trial placement groups, results stream back one
iteration at a time, schedulers stop/perturb trials, searchers generate
configs). PBT exploit/explore swaps checkpoints through the object store
(ref: tune/schedulers/pbt.py).
"""
from __future__ import annotations

import os
import re
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.core.placement_group import placement_group, remove_placement_group

from ..train.config import Result, RunConfig
from .schedulers import (CONTINUE, PAUSE, STOP, FIFOScheduler,
                         PopulationBasedTraining, TrialScheduler)
from .search import BasicVariantGenerator, Searcher
from .trainable import _TrialRunner

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class TuneConfig:
    """ref: python/ray/tune/tune_config.py"""
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    trial_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    reuse_actors: bool = False
    seed: Optional[int] = None


class Trial:
    _next = [0]

    @classmethod
    def next_id(cls) -> str:
        cls._next[0] += 1
        return f"trial_{cls._next[0]:05d}"

    def __init__(self, config: Dict[str, Any],
                 trial_id: Optional[str] = None):
        self.trial_id = trial_id or Trial.next_id()
        self.config = dict(config)
        self.status = PENDING
        self.runner = None
        self.pg = None
        self.future = None
        self.last_result: Optional[dict] = None
        self.metrics_history: List[dict] = []
        self.latest_checkpoint: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.pbt_ready = False
        # per-trial resource override (ResourceChangingScheduler); None
        # = the experiment-wide TuneConfig.trial_resources
        self.resources: Optional[Dict[str, float]] = None

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


class ResultGrid:
    """ref: python/ray/tune/result_grid.py"""

    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("No metric to rank results by")
        ok = [r for r in self._results
              if r.error is None and metric in (r.metrics or {})]
        if not ok:
            raise RuntimeError("No successful trial reported the metric")
        key = lambda r: float(r.metrics[metric])  # noqa: E731
        return (max if mode == "max" else min)(ok, key=key)

    def get_dataframe(self):
        rows = [dict(r.metrics or {}) for r in self._results]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except Exception:
            return rows


class TuneController:
    """Single-threaded event loop driving all trials
    (ref: tune_controller.py:49; step:267)."""

    def __init__(self, trainable: Any, param_space: Dict[str, Any],
                 tune_config: TuneConfig, run_config: RunConfig):
        self.tc = tune_config
        self.rc = run_config
        self._trainable_blob = cloudpickle.dumps(trainable)
        self.searcher = tune_config.search_alg or BasicVariantGenerator(
            num_samples=tune_config.num_samples, seed=tune_config.seed)
        self.searcher.set_space(dict(param_space or {}),
                                tune_config.metric, tune_config.mode)
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        if tune_config.metric:
            self.scheduler.set_metric(tune_config.metric, tune_config.mode)
        self.trials: List[Trial] = []
        self._exhausted = False
        # checkpoint cadence: PBT needs one per perturbation interval
        freq = run_config.checkpoint_config.checkpoint_frequency
        if not freq and isinstance(self.scheduler, PopulationBasedTraining):
            freq = 1
        self._ckpt_freq = freq
        self._exp_path: Optional[str] = None
        self._last_snapshot = 0.0
        self._syncer = None
        if run_config.upload_dir:
            from .syncer import Syncer

            self._syncer = Syncer(run_config.upload_dir,
                                  run_config.sync_period_s)

    # -- experiment state (ref: tune/execution/experiment_state.py
    # _ExperimentCheckpointManager: periodic driver-side snapshots that
    # Tuner.restore() resumes from) -----------------------------------------

    def snapshot_state(self) -> dict:
        trials = []
        for t in self.trials:
            trials.append({
                "trial_id": t.trial_id, "config": dict(t.config),
                # in-flight trials restart from their latest checkpoint
                "status": (PENDING if t.status in (RUNNING, PAUSED)
                           else t.status),
                "last_result": t.last_result,
                "metrics_history": list(t.metrics_history),
                "latest_checkpoint": t.latest_checkpoint,
            })
        return {"trials": trials, "searcher": self.searcher,
                "scheduler": self.scheduler, "exhausted": self._exhausted,
                "trainable_blob": self._trainable_blob,
                "metric": self.tc.metric, "mode": self.tc.mode}

    def load_state(self, state: dict) -> None:
        self.searcher = state["searcher"]
        self.scheduler = state["scheduler"]
        self._exhausted = bool(state["exhausted"])
        self.trials = []
        max_seq = 0
        for s in state["trials"]:
            t = Trial(s["config"], trial_id=s["trial_id"])
            t.status = s["status"]
            t.last_result = s["last_result"]
            t.metrics_history = list(s["metrics_history"])
            t.latest_checkpoint = s["latest_checkpoint"]
            self.trials.append(t)
            m = re.match(r"trial_(\d+)$", s["trial_id"])
            if m:
                max_seq = max(max_seq, int(m.group(1)))
        # new suggestions must not collide with restored trial ids
        Trial._next[0] = max(Trial._next[0], max_seq)

    def _maybe_snapshot(self, force: bool = False) -> None:
        if not self._exp_path:
            return
        now = time.monotonic()
        if not force and now - self._last_snapshot < 5.0:
            return
        self._last_snapshot = now
        os.makedirs(self._exp_path, exist_ok=True)
        path = os.path.join(self._exp_path, "experiment_state.pkl")
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                cloudpickle.dump(self.snapshot_state(), f)
            os.replace(tmp, path)  # atomic: a crash never truncates
        except Exception:  # noqa: BLE001 — snapshots are best-effort
            traceback.print_exc()
        if self._syncer is not None:
            self._syncer.sync_up(self._exp_path, force=force)

    # -- scheduler-facing API (ref: pbt.py uses these) -----------------------

    def running_trials(self) -> List[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    def paused_trials(self) -> List[Trial]:
        return [t for t in self.trials if t.status == PAUSED]

    def all_trials(self) -> List[Trial]:
        return list(self.trials)

    def resume_trial(self, trial: Trial) -> None:
        """Un-pause: restart the runner from the pause checkpoint (ref:
        tune_controller.py _schedule_trial_resume)."""
        if trial.status != PAUSED:
            return
        try:
            self._start_runner(trial, checkpoint=trial.latest_checkpoint)
        except Exception as e:  # noqa: BLE001
            traceback.print_exc()
            self._finish(trial, ERROR, e)

    def stop_trial(self, trial: Trial) -> None:
        """Scheduler-initiated stop of a paused/running trial."""
        if trial.status in (RUNNING, PAUSED, PENDING):
            self._finish(trial, TERMINATED)

    def _pause_trial(self, trial: Trial) -> None:
        """Checkpoint, then RELEASE the actor + placement group — a paused
        trial must not hold resources or bracket-synchronized schedulers
        (HyperBand) deadlock the cluster (ref: the reference pauses via
        save+stop, trial_runner.py)."""
        try:
            trial.latest_checkpoint = ray_tpu.get(
                trial.runner.save.remote(), timeout=60)
        except Exception:
            pass
        self._stop_runner(trial)
        if trial.pg is not None:
            try:
                remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None
        trial.status = PAUSED

    def exploit_trial(self, trial: Trial, donor: Trial,
                      new_config: Dict[str, Any]) -> None:
        """PBT exploit+explore: trial adopts donor's checkpoint and a
        mutated config — implemented as an actor swap (ref: pbt.py
        _exploit; trial restore via checkpoint)."""
        try:
            donor_ckpt = ray_tpu.get(donor.runner.save.remote(), timeout=60)
        except Exception:
            return
        self._stop_runner(trial)
        trial.config = dict(new_config)
        trial.latest_checkpoint = donor_ckpt
        self._start_runner(trial, checkpoint=donor_ckpt)

    # -- trial lifecycle -----------------------------------------------------

    def _start_runner(self, trial: Trial, checkpoint: Optional[dict] = None):
        res = dict(trial.resources or self.tc.trial_resources)
        if trial.pg is None:
            trial.pg = placement_group([dict(res)], strategy="PACK")
            if not trial.pg.ready(timeout=60.0):
                raise RuntimeError(f"{trial.trial_id}: placement group not ready")
        cls = ray_tpu.remote(_TrialRunner)
        trial.runner = cls.options(
            num_cpus=res.get("CPU", 1.0),
            resources={k: v for k, v in res.items() if k != "CPU"},
            placement_group=trial.pg,
            placement_group_bundle_index=0,
        ).remote(self._trainable_blob, trial.config, checkpoint)
        trial.status = RUNNING
        trial.future = trial.runner.step.remote()

    def _stop_runner(self, trial: Trial) -> None:
        if trial.runner is not None:
            try:
                ray_tpu.kill(trial.runner)
            except Exception:
                pass
        trial.runner = None
        trial.future = None

    def _finish(self, trial: Trial, status: str,
                error: Optional[BaseException] = None) -> None:
        self._stop_runner(trial)
        if trial.pg is not None:
            try:
                remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None
        trial.status = status
        trial.error = error
        self.scheduler.on_complete(trial, trial.last_result)
        self.searcher.on_trial_complete(trial.trial_id, trial.last_result)

    def _should_stop(self, result: dict) -> bool:
        stop = getattr(self.rc, "stop", None) or {}
        for k, v in stop.items():
            if k in result and float(result[k]) >= float(v):
                return True
        return False

    def _maybe_checkpoint(self, trial: Trial, result: dict) -> None:
        it = int(result.get("training_iteration", 0))
        if self._ckpt_freq and it % self._ckpt_freq == 0:
            try:
                trial.latest_checkpoint = ray_tpu.get(
                    trial.runner.save.remote(), timeout=60)
            except Exception:
                pass

    # -- the loop ------------------------------------------------------------

    def _capacity(self) -> int:
        if self.tc.max_concurrent_trials:
            return self.tc.max_concurrent_trials
        cpus = ray_tpu.cluster_resources().get("CPU", 1.0)
        per = self.tc.trial_resources.get("CPU", 1.0) or 1.0
        return max(1, int(cpus / per))

    def _fill(self) -> None:
        cap = self._capacity()
        while len(self.running_trials()) < cap:
            pending = [t for t in self.trials if t.status == PENDING]
            if pending:
                t = pending[0]
            elif not self._exhausted:
                # custom searchers (TPE, ...) suggest indefinitely;
                # num_samples is the experiment's total-trial budget
                # (ref: tune_config.py num_samples applies to searchers)
                if self.tc.search_alg is not None \
                        and len(self.trials) >= self.tc.num_samples:
                    self._exhausted = True
                    return
                # the id handed to suggest() IS the trial's id — adaptive
                # searchers key their pending suggestions by it and match
                # it again in on_trial_complete
                tid = Trial.next_id()
                cfg = self.searcher.suggest(tid)
                if cfg is None:
                    self._exhausted = True
                    return
                if cfg is Searcher.PENDING:
                    # not exhausted — the searcher (ConcurrencyLimiter,
                    # batched BO) wants results back first; retry on the
                    # next loop tick
                    return
                t = Trial(cfg, trial_id=tid)
                self.trials.append(t)
            else:
                return
            try:
                # restored trials resume from their snapshot checkpoint
                self._start_runner(t, checkpoint=t.latest_checkpoint)
            except Exception as e:  # noqa: BLE001
                traceback.print_exc()
                self._finish(t, ERROR, e)

    def run(self) -> List[Trial]:
        while True:
            self._fill()
            self._maybe_snapshot()
            active = {t.future: t for t in self.running_trials()
                      if t.future is not None}
            if not active:
                pending = [t for t in self.trials if t.status == PENDING]
                paused = self.paused_trials()
                if not pending and paused:
                    # nothing running, nothing to start: rung populations
                    # can never complete — the scheduler must force
                    # progress (promote/stop from incomplete rungs)
                    self.scheduler.choose_action(self)
                    if not self.running_trials():
                        self.scheduler.on_deadlock(self)
                    if self.running_trials() or \
                            [t for t in self.trials if t.status == PENDING]:
                        continue
                    break  # scheduler refused to act: avoid spinning
                if not pending and self._exhausted:
                    break
                if not pending and not self.trials:
                    self._exhausted = True  # empty space: nothing to do
                    break
                continue
            # Drain EVERY completed future this pass, so trials advance one
            # iteration per loop in round-robin rather than one trial
            # running to completion first — ASHA's rung cutoffs need
            # interleaved arrivals to have a comparison population (ref:
            # tune_controller.py step:267 processes events fairly).
            done, _ = ray_tpu.wait(list(active), num_returns=len(active),
                                   timeout=0.2)
            if not done:
                done, _ = ray_tpu.wait(list(active), num_returns=1,
                                       timeout=5.0)
            for fut in done:
                trial = active[fut]
                try:
                    result = ray_tpu.get(fut)
                except Exception as e:  # noqa: BLE001 — trial failure
                    self._finish(trial, ERROR, e)
                    continue
                if result is None:
                    self._finish(trial, TERMINATED)
                    continue
                trial.last_result = result
                trial.metrics_history.append(result)
                self._maybe_checkpoint(trial, result)
                # hook probe (not try/except — that would also swallow
                # AttributeErrors raised INSIDE a searcher's own hook)
                hook = getattr(self.searcher, "on_trial_result", None)
                if hook is not None:
                    hook(trial.trial_id, result)
                decision = self.scheduler.on_result(trial, result)
                if decision == STOP or self._should_stop(result):
                    self._finish(trial, TERMINATED)
                elif decision == PAUSE:
                    self._pause_trial(trial)
                    self.scheduler.choose_action(self)
                else:
                    # PBT may swap the runner (and queue a fresh step)
                    # underneath us — only re-issue if the consumed future
                    # is still the trial's current one.
                    self.scheduler.choose_action(self)
                    if (trial.status == RUNNING and trial.runner is not None
                            and trial.future is fut):
                        trial.future = trial.runner.step.remote()
            self.scheduler.choose_action(self)
        # let composite searchers flush partial state (Repeater groups
        # truncated by the num_samples budget)
        end_hook = getattr(self.searcher, "on_experiment_end", None)
        if end_hook is not None:
            end_hook()
        return self.trials


class Tuner:
    """ref: python/ray/tune/tuner.py:320. Also accepts a Train trainer
    instance (ref: train/base_trainer.py:829 — a Trainer becomes a
    Trainable): param_space keys override the trainer's train_loop_config.
    """

    def __init__(self, trainable: Any = None, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        from ..train.trainer import DataParallelTrainer

        if isinstance(trainable, DataParallelTrainer):
            trainable = _trainer_to_trainable(trainable)
        self.trainable = trainable
        self.param_space = dict(param_space or {})
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    _restore_state: Optional[dict] = None

    @classmethod
    def restore(cls, path: str, trainable: Any = None, *,
                param_space: Optional[Dict[str, Any]] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its state snapshot (ref:
        tuner.py:200 Tuner.restore + experiment_state.py). Finished trials
        keep their results; in-flight trials restart from their latest
        checkpoint; the searcher/scheduler continue with their state."""
        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            state = cloudpickle.load(f)
        if trainable is None:
            trainable = cloudpickle.loads(state["trainable_blob"])
        if tune_config is None:
            tune_config = TuneConfig(metric=state.get("metric"),
                                     mode=state.get("mode") or "max")
        rc = run_config or RunConfig()
        if os.path.isdir(path):
            # pin storage back to the restored experiment directory
            rc.storage_path = os.path.dirname(path.rstrip(os.sep)) or path
            rc.name = os.path.basename(path.rstrip(os.sep))
        tuner = cls(trainable, param_space=param_space,
                    tune_config=tune_config, run_config=rc)
        tuner._restore_state = state
        return tuner

    def fit(self) -> ResultGrid:
        controller = TuneController(self.trainable, self.param_space,
                                    self.tune_config, self.run_config)
        base = self.run_config.resolved_storage_path()
        controller._exp_path = base
        if self._restore_state is not None:
            controller.load_state(self._restore_state)
        trials = controller.run()
        controller._maybe_snapshot(force=True)
        if controller._syncer is not None:
            controller._syncer.close()
        os.makedirs(base, exist_ok=True)
        results = []
        for t in trials:
            ck = None
            if t.latest_checkpoint:
                from ..train.checkpoint import Checkpoint

                ck = Checkpoint.from_dict(t.latest_checkpoint)
            metrics = dict(t.last_result or {})
            # the trial's config rides along (ref: ResultGrid results carry
            # .config; experiment_analysis.py merges config into dataframes)
            metrics.setdefault("config", dict(t.config))
            results.append(Result(
                metrics=metrics,
                checkpoint=ck,
                path=os.path.join(base, t.trial_id),
                error=t.error,
                metrics_history=list(t.metrics_history)))
        return ResultGrid(results, self.tune_config.metric,
                          self.tune_config.mode)


def _trainer_to_trainable(trainer) -> Callable:
    """Wrap a DataParallelTrainer so each trial re-fits it with the trial
    config merged into train_loop_config, streaming history entries as
    reports (ref: base_trainer.py:829 as_trainable)."""
    import copy

    from . import session as _sess

    base = trainer

    def train_fn(config: Dict[str, Any]) -> None:
        t = copy.copy(base)
        t.train_config = {**base.train_config, **config}
        result = t.fit()
        if result.error is not None:
            raise result.error
        for entry in result.metrics_history or [result.metrics or {}]:
            _sess.report(dict(entry))

    return train_fn


def run(trainable, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler: Optional[TrialScheduler] = None,
        stop: Optional[Dict[str, Any]] = None,
        **kw) -> ResultGrid:
    """Legacy-style entry point (ref: tune/tune.py:292 tune.run)."""
    rc = RunConfig()
    if stop:
        rc.stop = stop  # type: ignore[attr-defined]
    return Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler),
        run_config=rc).fit()
